"""Profiler bridge + engine fence (parity: [U:tests/python/unittest/
test_profiler.py] control-surface checks, the round-3 device-op aggregate
table and multi-device waitall, plus the ISSUE-5 tracing subsystem: span
recorder / chrome-trace round trip, per-step telemetry, slow-step
detector, strict counters, and the trace_report CLI)."""
import json
import logging
import os
import subprocess
import sys
import threading
import time
from collections import defaultdict

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd, engine, profiler
from incubator_mxnet_tpu.gluon import Trainer, nn

import jax

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def clean_profiler(tmp_path):
    """Arm-safe profiler state: fresh filename, stopped recorder, zeroed
    counters before AND after (profiler state is module-global)."""
    profiler.stop()
    profiler.set_config(filename=str(tmp_path / "trace.json"),
                        ring_size=65536, slow_step_ms=None)
    profiler.reset_counters()
    yield tmp_path
    profiler.stop()
    profiler.set_config(slow_step_ms=None, ring_size=65536,
                        slow_step_auto=True, memory_sampling=True)
    profiler.reset_counters()


def _paired_spans(events):
    """Pair B/E events per (pid, tid); returns the B events (with their
    matching E verified) and asserts nothing is unpaired."""
    stacks = defaultdict(list)
    spans = []
    for e in sorted((e for e in events if e.get("ph") in ("B", "E")),
                    key=lambda e: e["ts"]):
        k = (e["pid"], e["tid"])
        if e["ph"] == "B":
            stacks[k].append(e)
        else:
            assert stacks[k], f"E without open B at ts={e['ts']}"
            b = stacks[k].pop()
            assert e["ts"] >= b["ts"]
            b["_end"] = e["ts"]
            spans.append(b)
    assert not any(stacks.values()), "B events left unclosed"
    return spans


class TestProfiler:
    def test_scope_and_dumps(self):
        with profiler.scope("unit_region"):
            (mx.nd.ones((8, 8)) * 2).asnumpy()
        s = profiler.dumps()
        assert "Profile Statistics" in s
        assert "unit_region" in s

    def test_device_op_stats_parses_synthetic_xplane(self, tmp_path):
        from tensorflow.tsl.profiler.protobuf import xplane_pb2

        xs = xplane_pb2.XSpace()
        plane = xs.planes.add()
        plane.name = "/device:TPU:0"
        md = plane.event_metadata[1]
        md.id = 1
        md.name = "%fusion.42 = f32[8,8]{1,0} fusion(%p0), kind=kLoop"
        line = plane.lines.add()
        line.name = "XLA Ops"
        for _ in range(3):
            ev = line.events.add()
            ev.metadata_id = 1
            ev.duration_ps = int(2e9)  # 2 ms each
        d = tmp_path / "t"
        d.mkdir()
        with open(d / "host.xplane.pb", "wb") as f:
            f.write(xs.SerializeToString())
        rows = profiler._device_op_stats(str(d))
        assert len(rows) == 1
        name, count, total_s = rows[0]
        assert (name, count) == ("fusion", 3)
        # 3 events × 2e9 ps = 6e9 ps = 6 ms
        np.testing.assert_allclose(total_s, 6e-3, rtol=1e-9)

    def test_dumps_mentions_device_section_after_start_stop(self, tmp_path):
        profiler.set_config(filename=str(tmp_path / "prof.json"))
        profiler.start()
        (mx.nd.ones((16, 16)) @ mx.nd.ones((16, 16))).asnumpy()
        profiler.stop()
        s = profiler.dumps()
        assert "Profile Statistics" in s  # device rows depend on backend


def test_waitall_covers_all_devices():
    # dispatch work on every device of the 8-device mesh, then fence
    outs = []
    for d in jax.local_devices():
        x = jax.device_put(np.arange(1024.0), d)
        outs.append(x * 2 + 1)
    mx.nd.waitall()
    for o in outs:
        # after waitall every per-device queue has drained; reads are instant
        assert np.isfinite(np.asarray(o)).all()


# ---------------------------------------------------------------------------
# ISSUE 5: span recorder + chrome-trace round trip
# ---------------------------------------------------------------------------


class TestChromeTrace:
    def test_train_trace_roundtrip(self, clean_profiler):
        """The acceptance loop: start(); 3 train steps; dump() -> a
        chrome://tracing-valid JSON with spans from the dispatch-cache,
        bulk-flush, fused-step, and kvstore categories, each tagged with
        the correct (monotone) step id."""
        net = nn.Dense(8)
        net.initialize()
        trainer = Trainer(net.collect_params(), "sgd",
                          {"learning_rate": 0.1, "momentum": 0.9},
                          kvstore="device")
        x = mx.nd.ones((4, 16))

        profiler.start()
        first_step = profiler.current_step()
        for _ in range(3):
            with autograd.record():
                loss = (net(x) ** 2).sum()
            loss.backward()
            with engine.bulk(8):  # eager metric chain -> bulk spans
                m = loss + 0.0
                for _ in range(4):
                    m = m * 1.0
            m.asnumpy()
            trainer.step(4)
        path = profiler.dump()

        with open(path) as f:
            doc = json.load(f)
        assert isinstance(doc["traceEvents"], list)
        spans = _paired_spans(doc["traceEvents"])
        cats = {s["cat"] for s in spans}
        assert {"dispatch", "bulk", "optimizer", "comms", "step",
                "trainer"} <= cats

        # step ids: monotone per thread in timestamp order
        per_tid = defaultdict(list)
        for s in sorted(spans, key=lambda s: s["ts"]):
            per_tid[s["tid"]].append(s["args"]["step"])
        for ids in per_tid.values():
            assert ids == sorted(ids)

        # step ids: CORRECT — every span inside a step span's [B, E] range
        # carries that step's id (asserted for the synchronous train-loop
        # categories; the three steps are first_step..first_step+2)
        step_spans = sorted((s for s in spans if s["cat"] == "step"),
                            key=lambda s: s["ts"])
        assert [s["args"]["step"] for s in step_spans] == [
            first_step, first_step + 1, first_step + 2]
        for s in spans:
            if s["cat"] not in ("optimizer", "comms", "trainer"):
                continue
            owner = [st for st in step_spans
                     if st["ts"] <= s["ts"] and s["_end"] <= st["_end"]]
            assert owner, f"span {s['name']} outside every step"
            assert s["args"]["step"] == owner[0]["args"]["step"]

        # at least one span of each acceptance name family
        names = {s["name"] for s in spans}
        assert "fused.group_apply" in names
        assert "bulk.flush" in names
        assert "kvstore.pushpull" in names
        assert names & {"dispatch.cache_hit", "dispatch.jit_compile"}

        # telemetry rode along: 3 closed steps with bucket splits
        steps = profiler.step_stats()[-3:]
        assert [s["step"] for s in steps] == [first_step, first_step + 1,
                                              first_step + 2]
        for s in steps:
            assert s["wall_ms"] >= s["host_ms"] >= 0
            assert s["device_ms"] >= 0

    def test_dump_finished_false_keeps_recording(self, clean_profiler):
        profiler.start()
        with profiler.span("before", "user"):
            pass
        path = profiler.dump(finished=False)
        assert profiler.state() == "running"
        assert profiler.recording_enabled()
        with profiler.span("after", "user"):
            pass
        path = profiler.dump()  # default finishes
        assert profiler.state() == "stopped"
        assert not profiler.recording_enabled()
        names = {s["name"] for s in
                 _paired_spans(json.load(open(path))["traceEvents"])}
        assert {"before", "after"} <= names

    def test_multithreaded_span_counts(self, clean_profiler):
        """Exact per-thread span counts under concurrency: the per-thread
        rings may not drop or duplicate spans."""
        n_threads, n_spans = 4, 250
        profiler.start()
        barrier = threading.Barrier(n_threads)

        def work():
            barrier.wait()
            for i in range(n_spans):
                t0 = time.perf_counter()
                profiler.record_span(f"mt_{i % 7}", "user", t0)

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        profiler.stop()
        spans = _paired_spans(profiler._trace_events())
        per_tid = defaultdict(int)
        for s in spans:
            if s["name"].startswith("mt_"):
                per_tid[s["tid"]] += 1
        assert len(per_tid) == n_threads
        assert all(c == n_spans for c in per_tid.values())

    def test_ring_buffer_bounds_memory(self, clean_profiler):
        """Recording more spans than the ring capacity must not grow
        memory: the oldest spans are evicted and counted as dropped."""
        profiler.set_config(ring_size=64)
        profiler.start()
        for i in range(200):
            t0 = time.perf_counter()
            profiler.record_span(f"ring_{i}", "user", t0)
        stats = profiler.recorder_stats()
        profiler.stop()
        assert stats["spans"] == 64
        assert stats["dropped"] == 200 - 64
        spans = _paired_spans(profiler._trace_events())
        kept = sorted(int(s["name"].split("_")[1]) for s in spans
                      if s["name"].startswith("ring_"))
        assert kept == list(range(136, 200))  # oldest evicted, newest kept

    def test_ring_registry_bounded_under_thread_churn(self, clean_profiler):
        """Short-lived threads (a fresh prefetch worker per epoch) must not
        grow the retained-rings list without bound: dead threads' rings are
        evicted once the cap is exceeded."""
        profiler.set_config(ring_size=8)
        profiler.start()
        for i in range(profiler._MAX_RINGS + 20):
            t = threading.Thread(
                target=lambda: profiler.record_span("churn", "user",
                                                    time.perf_counter()))
            t.start()
            t.join()
        n_rings = profiler.recorder_stats()["threads"]
        profiler.stop()
        # cap + the handful of genuinely-alive threads at eviction time
        assert n_rings <= profiler._MAX_RINGS + 1


# ---------------------------------------------------------------------------
# ISSUE 5: per-step telemetry + slow-step detector
# ---------------------------------------------------------------------------


class TestStepTelemetry:
    def test_slow_step_detector_fires_exactly_once(self, clean_profiler,
                                                   caplog):
        profiler.set_config(slow_step_ms=50.0)
        profiler.start()
        with caplog.at_level(logging.WARNING,
                             logger="incubator_mxnet_tpu.profiler"):
            for _ in range(4):      # normal steps: well under 50 ms
                profiler.step_boundary()
            time.sleep(0.08)        # injected stall
            profiler.step_boundary()
            for _ in range(4):      # back to normal
                profiler.step_boundary()
        profiler.stop()
        slow_lines = [r for r in caplog.records if "slow step" in r.message]
        assert len(slow_lines) == 1
        msg = slow_lines[0].getMessage()
        assert "host-dispatch" in msg and "comms" in msg
        assert profiler.counters()["slow_step_detected"] == 1

    def test_slow_step_auto_percentile_mode(self, clean_profiler, caplog):
        """No explicit threshold: a step > mult x the rolling median is
        flagged once the window has enough history."""
        profiler.set_config(slow_step_ms=None, slow_step_auto=True,
                            slow_step_auto_mult=4.0)
        profiler.start()
        with caplog.at_level(logging.WARNING,
                             logger="incubator_mxnet_tpu.profiler"):
            for _ in range(20):
                time.sleep(0.01)
                profiler.step_boundary()
            time.sleep(0.3)         # >> 4x the ~10 ms median
            profiler.step_boundary()
        profiler.stop()
        auto = [r for r in caplog.records if "auto:" in r.message]
        assert len(auto) == 1

    def test_step_buckets_accumulate(self, clean_profiler):
        profiler.start()
        sid = profiler.current_step()
        t0 = time.perf_counter()
        profiler.record_span("kvstore.pushpull", "comms", t0, t0 + 0.010)
        profiler.record_span("dispatch.cache_hit", "dispatch", t0, t0 + 0.005)
        profiler.record_span("bulk.trace", "bulk", t0, t0 + 0.003)  # nested:
        profiler.step_boundary()                    # excluded from buckets
        profiler.stop()
        s = [s for s in profiler.step_stats() if s["step"] == sid][-1]
        assert s["comms_ms"] == pytest.approx(10.0, rel=0.3)
        assert s["host_ms"] == pytest.approx(5.0, rel=0.3)

    def test_memory_watermark_surface(self, clean_profiler):
        # CPU devices may expose no memory_stats: the sampler must stay
        # silent/empty, never raise
        profiler.start()
        profiler.step_boundary()
        profiler.step_boundary()
        profiler.stop()
        wm = profiler.memory_watermark()
        assert isinstance(wm, dict)
        assert all(isinstance(v, int) and v >= 0 for v in wm.values())


# ---------------------------------------------------------------------------
# ISSUE 5 satellites: strict counters, locked _tally, trace-error surfacing
# ---------------------------------------------------------------------------


class TestCounters:
    def test_incr_unknown_name_raises(self):
        typo = "dispatch_cache_hti"  # built dynamically elsewhere this
        with pytest.raises(KeyError):  # would silently report zeros forever
            profiler.incr(typo)

    def test_declare_counter_extension_path(self):
        profiler.declare_counter("test_custom_counter")
        profiler.incr("test_custom_counter", 3)
        assert profiler.counters()["test_custom_counter"] == 3
        profiler.reset_counters()
        assert profiler.counters()["test_custom_counter"] == 0

    def test_incr_exact_under_concurrency(self):
        profiler.reset_counters()
        n_threads, n_incr = 8, 500

        def work():
            for _ in range(n_incr):
                profiler.incr("dispatch_cache_hit")

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert profiler.counters()["dispatch_cache_hit"] == n_threads * n_incr
        profiler.reset_counters()

    def test_tally_exact_under_concurrency(self):
        """Satellite 1: concurrent scopes must not drop _agg tallies (the
        old unlocked read-modify-write did) and dumps() must iterate a
        stable snapshot."""
        name = "tally_race_probe"
        with profiler._counter_lock:
            profiler._agg.pop(name, None)
        n_threads, n_tallies = 8, 400
        stop = threading.Event()

        def dump_loop():  # concurrent reader: would blow up on a mutating
            while not stop.is_set():  # dict pre-fix
                profiler.dumps()

        reader = threading.Thread(target=dump_loop)
        reader.start()

        def work():
            for _ in range(n_tallies):
                profiler._tally(name, 0.001)

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stop.set()
        reader.join()
        cnt, tot = profiler._agg[name]
        assert cnt == n_threads * n_tallies
        assert tot == pytest.approx(cnt * 0.001)
        with profiler._counter_lock:
            profiler._agg.pop(name, None)

    def test_trace_error_warns_once_and_counts(self, clean_profiler,
                                               monkeypatch):
        """Satellite 3: a broken xprof install is diagnosable — RuntimeWarning
        (once) + profiler_trace_error counter, and the span recorder still
        arms."""
        def boom(*a, **k):
            raise RuntimeError("no xprof here")

        monkeypatch.setattr(jax.profiler, "start_trace", boom)
        monkeypatch.setattr(profiler, "_trace_warned", False)
        with pytest.warns(RuntimeWarning, match="profiler_trace_error"):
            profiler.start()
        assert profiler.recording_enabled()  # python spans still captured
        assert profiler.counters()["profiler_trace_error"] == 1
        profiler.stop()  # must not call stop_trace (xprof never started)
        assert profiler.counters()["profiler_trace_error"] == 1


# ---------------------------------------------------------------------------
# ISSUE 5: disabled-recorder overhead + trace_report CLI
# ---------------------------------------------------------------------------


def test_disabled_recorder_overhead_smoke():
    """The eager-dispatch chain runs with the recorder OFF: no spans may be
    recorded and the benchmark harness must be unperturbed (the <3% number
    is measured by the full paired-median run, not asserted here)."""
    import importlib.util

    profiler.stop()
    assert not profiler.recording_enabled()
    before = profiler.recorder_stats()["spans"]
    path = os.path.join(_REPO, "benchmark", "opperf", "eager_dispatch.py")
    spec = importlib.util.spec_from_file_location("eager_dispatch_bench", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    line = mod.run(n_ops=6, iters=2, shape=(4, 4), warmup=1)
    for mode in ("uncached", "cached_jit", "bulked"):
        assert line["ops_per_sec"][mode]["elemwise"] > 0
    assert profiler.recorder_stats()["spans"] == before


class TestTraceReport:
    def _synthetic_trace(self, path):
        evs = []
        t = 1000.0
        for step in (1, 2, 3):
            evs.append({"ph": "B", "name": "step", "cat": "step", "ts": t,
                        "pid": 1, "tid": 7, "args": {"step": step}})
            evs.append({"ph": "B", "name": "fused.group_apply",
                        "cat": "optimizer", "ts": t + 10, "pid": 1,
                        "tid": 7, "args": {"step": step}})
            evs.append({"ph": "E", "name": "fused.group_apply",
                        "cat": "optimizer", "ts": t + 60, "pid": 1, "tid": 7})
            evs.append({"ph": "E", "name": "step", "cat": "step",
                        "ts": t + 100, "pid": 1, "tid": 7})
            t += 200
        doc = {"traceEvents": evs, "displayTimeUnit": "ms",
               "otherData": {"steps": [
                   {"step": s, "wall_ms": 0.1, "host_ms": 0.05,
                    "comms_ms": 0.0, "device_ms": 0.05} for s in (1, 2, 3)]}}
        with open(path, "w") as f:
            json.dump(doc, f)
        return path

    def test_report_on_synthetic_trace(self, tmp_path):
        trace = self._synthetic_trace(str(tmp_path / "synth.json"))
        out = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "trace_report.py"),
             trace, "--top", "5"],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        assert "Per-category totals" in out.stdout
        assert "optimizer" in out.stdout
        assert "Step-time histogram" in out.stdout
        assert "fused.group_apply" in out.stdout

    def test_report_rejects_invalid_trace(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text("{not json")
        out = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "trace_report.py"),
             str(bad)],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 2

    def test_report_on_real_dump(self, clean_profiler, tmp_path):
        profiler.start()
        with profiler.span("real_work", "user"):
            (mx.nd.ones((8, 8)) * 3).asnumpy()
        profiler.step_boundary()
        path = profiler.dump()
        out = subprocess.run(
            [sys.executable, os.path.join(_REPO, "tools", "trace_report.py"),
             path], capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        assert "real_work" in out.stdout
