"""Profiler bridge + engine fence (parity: [U:tests/python/unittest/
test_profiler.py] control-surface checks, plus the round-3 device-op
aggregate table and multi-device waitall)."""
import os

import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import profiler

import jax


class TestProfiler:
    def test_scope_and_dumps(self):
        with profiler.scope("unit_region"):
            (mx.nd.ones((8, 8)) * 2).asnumpy()
        s = profiler.dumps()
        assert "Profile Statistics" in s
        assert "unit_region" in s

    def test_device_op_stats_parses_synthetic_xplane(self, tmp_path):
        from tensorflow.tsl.profiler.protobuf import xplane_pb2

        xs = xplane_pb2.XSpace()
        plane = xs.planes.add()
        plane.name = "/device:TPU:0"
        md = plane.event_metadata[1]
        md.id = 1
        md.name = "%fusion.42 = f32[8,8]{1,0} fusion(%p0), kind=kLoop"
        line = plane.lines.add()
        line.name = "XLA Ops"
        for _ in range(3):
            ev = line.events.add()
            ev.metadata_id = 1
            ev.duration_ps = int(2e9)  # 2 ms each
        d = tmp_path / "t"
        d.mkdir()
        with open(d / "host.xplane.pb", "wb") as f:
            f.write(xs.SerializeToString())
        rows = profiler._device_op_stats(str(d))
        assert len(rows) == 1
        name, count, total_s = rows[0]
        assert (name, count) == ("fusion", 3)
        # 3 events × 2e9 ps = 6e9 ps = 6 ms
        np.testing.assert_allclose(total_s, 6e-3, rtol=1e-9)

    def test_dumps_mentions_device_section_after_start_stop(self, tmp_path):
        profiler.set_config(filename=str(tmp_path / "prof.json"))
        profiler.start()
        (mx.nd.ones((16, 16)) @ mx.nd.ones((16, 16))).asnumpy()
        profiler.stop()
        s = profiler.dumps()
        assert "Profile Statistics" in s  # device rows depend on backend


def test_waitall_covers_all_devices():
    # dispatch work on every device of the 8-device mesh, then fence
    outs = []
    for d in jax.local_devices():
        x = jax.device_put(np.arange(1024.0), d)
        outs.append(x * 2 + 1)
    mx.nd.waitall()
    for o in outs:
        # after waitall every per-device queue has drained; reads are instant
        assert np.isfinite(np.asarray(o)).all()
