"""Loss tests (parity model: [U:tests/python/unittest/test_loss.py])."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon
from incubator_mxnet_tpu.utils.test_utils import assert_almost_equal


def test_l2_loss():
    loss = gluon.loss.L2Loss()
    pred = mx.nd.array([[1.0, 2.0], [3.0, 4.0]])
    label = mx.nd.array([[1.5, 2.0], [3.0, 3.0]])
    out = loss(pred, label)
    expect = ((np.array([[0.5, 0], [0, 1.0]]) ** 2) / 2).mean(axis=1)
    assert_almost_equal(out, expect, rtol=1e-5, atol=1e-6)


def test_l1_loss():
    loss = gluon.loss.L1Loss()
    pred = mx.nd.array([[1.0, -1.0]])
    label = mx.nd.array([[0.0, 0.0]])
    assert float(loss(pred, label).asscalar()) == pytest.approx(1.0)


def test_softmax_ce_sparse_matches_manual():
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    logits = mx.nd.array([[1.0, 2.0, 3.0], [1.0, 1.0, 1.0]])
    label = mx.nd.array([2, 0])
    out = loss(logits, label).asnumpy()
    p = np.exp(logits.asnumpy())
    p /= p.sum(axis=1, keepdims=True)
    manual = -np.log(p[np.arange(2), [2, 0]])
    assert_almost_equal(out, manual, rtol=1e-5, atol=1e-6)


def test_softmax_ce_dense_label():
    loss = gluon.loss.SoftmaxCrossEntropyLoss(sparse_label=False)
    logits = mx.nd.array([[1.0, 2.0, 3.0]])
    label = mx.nd.array([[0.0, 0.0, 1.0]])
    sparse = gluon.loss.SoftmaxCrossEntropyLoss()(logits, mx.nd.array([2]))
    assert_almost_equal(loss(logits, label), sparse, rtol=1e-5, atol=1e-6)


def test_sigmoid_bce():
    loss = gluon.loss.SigmoidBCELoss()
    pred = mx.nd.array([[0.0]])
    label = mx.nd.array([[1.0]])
    assert float(loss(pred, label).asscalar()) == pytest.approx(np.log(2), rel=1e-4)


def test_kl_div():
    loss = gluon.loss.KLDivLoss()
    logp = mx.nd.log(mx.nd.array([[0.25, 0.75]]))
    label = mx.nd.array([[0.25, 0.75]])
    assert float(loss(logp, label).asscalar()) == pytest.approx(0.0, abs=1e-5)


def test_huber():
    loss = gluon.loss.HuberLoss(rho=1.0)
    pred = mx.nd.array([[0.0, 0.0]])
    label = mx.nd.array([[0.5, 3.0]])
    out = float(loss(pred, label).asscalar())
    assert out == pytest.approx((0.5 * 0.25 + (3.0 - 0.5)) / 2, rel=1e-4)


def test_hinge():
    loss = gluon.loss.HingeLoss()
    pred = mx.nd.array([[0.5], [2.0]])
    label = mx.nd.array([[1.0], [1.0]])
    out = loss(pred, label).asnumpy()
    assert_almost_equal(out, np.array([0.5, 0.0]), rtol=1e-5, atol=1e-6)


def test_triplet():
    loss = gluon.loss.TripletLoss(margin=1.0)
    anchor = mx.nd.array([[0.0, 0.0]])
    pos = mx.nd.array([[0.1, 0.0]])
    neg = mx.nd.array([[2.0, 0.0]])
    out = float(loss(anchor, pos, neg).asscalar())
    assert out == pytest.approx(0.0)  # relu(0.01 - 4 + 1) = 0


def test_ctc_loss_simple():
    """CTC on a trivial 1-label problem: strong evidence for the label."""
    loss = gluon.loss.CTCLoss(layout="TNC")
    T, B, C = 4, 1, 3
    logits = np.full((T, B, C), -5.0, dtype="float32")
    logits[:, 0, 1] = 5.0  # label 1 everywhere
    label = mx.nd.array([[1]])
    out = float(loss(mx.nd.array(logits), label).asscalar())
    assert np.isfinite(out)
    # strong-evidence sequence should have small loss
    assert out < 1.0


def test_loss_weight_and_sample_weight():
    loss = gluon.loss.L1Loss(weight=2.0)
    pred = mx.nd.array([[1.0]])
    label = mx.nd.array([[0.0]])
    assert float(loss(pred, label).asscalar()) == pytest.approx(2.0)
    loss2 = gluon.loss.L1Loss()
    sw = mx.nd.array([[0.0]])
    assert float(loss2(pred, label, sw).asscalar()) == pytest.approx(0.0)


def test_sdml_loss():
    """SDMLLoss (round-5 tail): smoothed in-batch contrastive CE — matched
    pairs beat shuffled pairs; gradient flows; training pulls pairs
    together."""
    from incubator_mxnet_tpu import autograd

    rng = np.random.RandomState(0)
    x1 = mx.nd.array(rng.randn(6, 8).astype(np.float32))
    L = gluon.loss.SDMLLoss(smoothing_parameter=0.3)
    matched = float(L(x1, x1 * 1.01).asnumpy().mean())
    shuffled = float(L(x1, mx.nd.array(x1.asnumpy()[::-1].copy())).asnumpy().mean())
    assert matched < shuffled
    x1.attach_grad()
    with autograd.record():
        val = L(x1, x1 * 0.99).sum()
    val.backward()
    assert np.isfinite(x1.grad.asnumpy()).all()
    with pytest.raises(ValueError):
        L(mx.nd.zeros((1, 4)), mx.nd.zeros((1, 4)))
