"""Module API tests (parity idioms: tests/python/unittest/test_module.py —
fit to accuracy, checkpoint round-trip, bucketing)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import sym

from common import with_seed


def _toy_problem(n=600, d=20, k=3, seed=42):
    rng = np.random.RandomState(seed)
    W = rng.randn(k, d)
    X = rng.randn(n, d).astype(np.float32)
    Y = (X @ W.T).argmax(axis=1).astype(np.float32)
    return X, Y


def _mlp_sym(hidden=32, k=3):
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=hidden, name="fc1")
    net = sym.Activation(net, act_type="relu", name="a1")
    net = sym.FullyConnected(net, num_hidden=k, name="fc2")
    return sym.SoftmaxOutput(net, label=sym.Variable("softmax_label"),
                             name="softmax", normalization="batch")


class TestNDArrayIter:
    def test_basic_epoch(self):
        X = np.arange(20, dtype=np.float32).reshape(10, 2)
        Y = np.arange(10, dtype=np.float32)
        it = mx.io.NDArrayIter(X, Y, batch_size=4, last_batch_handle="pad")
        batches = list(it)
        assert len(batches) == 3
        assert batches[0].data[0].shape == (4, 2)
        assert batches[-1].pad == 2
        it.reset()
        assert len(list(it)) == 3

    def test_discard(self):
        X = np.zeros((10, 2), np.float32)
        it = mx.io.NDArrayIter(X, None, batch_size=4, last_batch_handle="discard")
        assert len(list(it)) == 2

    def test_shuffle_covers_all(self):
        X = np.arange(12, dtype=np.float32).reshape(12, 1)
        it = mx.io.NDArrayIter(X, None, batch_size=4, shuffle=True)
        seen = np.concatenate([b.data[0].asnumpy().ravel() for b in it])
        assert sorted(seen.tolist()) == list(range(12))

    def test_resize_iter(self):
        X = np.zeros((8, 2), np.float32)
        it = mx.io.ResizeIter(mx.io.NDArrayIter(X, None, batch_size=4), size=5)
        assert len(list(it)) == 5

    def test_prefetching_iter(self):
        X = np.arange(16, dtype=np.float32).reshape(16, 1)
        base = mx.io.NDArrayIter(X, None, batch_size=4)
        pf = mx.io.PrefetchingIter(base)
        got = [b.data[0].asnumpy() for b in pf]
        assert len(got) == 4
        pf.reset()
        assert len(list(pf)) == 4


class TestModule:
    @with_seed()
    def test_fit_reaches_accuracy(self):
        # recipe chosen for seed-robustness: worst-case val acc over a seed
        # sweep is ~0.87, so the 0.8 bar has real margin under rotating seeds
        X, Y = _toy_problem()
        train = mx.io.NDArrayIter(X[:500], Y[:500], batch_size=50, shuffle=True)
        val = mx.io.NDArrayIter(X[500:], Y[500:], batch_size=50)
        mod = mx.mod.Module(_mlp_sym(hidden=64), context=mx.cpu())
        mod.fit(train, optimizer="sgd",
                optimizer_params={"learning_rate": 0.25, "momentum": 0.9},
                num_epoch=40, initializer=mx.initializer.Xavier(magnitude=2.0))
        acc = mod.score(val, "acc")[0][1]
        assert acc > 0.8, acc

    def test_checkpoint_roundtrip(self, tmp_path):
        X, Y = _toy_problem(n=200)
        train = mx.io.NDArrayIter(X, Y, batch_size=50)
        mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
        mod.fit(train, optimizer="sgd",
                optimizer_params={"learning_rate": 0.2, "momentum": 0.9},
                num_epoch=3, initializer=mx.initializer.Xavier())
        ref = mod.score(train, "acc")[0][1]
        prefix = str(tmp_path / "ckpt")
        mod.save_checkpoint(prefix, 3)

        mod2 = mx.mod.Module.load(prefix, 3)
        mod2.bind(train.provide_data, train.provide_label, for_training=False)
        mod2.init_params()
        assert abs(mod2.score(train, "acc")[0][1] - ref) < 1e-6

    def test_predict_strips_pad(self):
        X, Y = _toy_problem(n=110)
        it = mx.io.NDArrayIter(X, Y, batch_size=50, last_batch_handle="pad")
        mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
        mod.bind(it.provide_data, it.provide_label, for_training=False)
        mod.init_params(initializer=mx.initializer.Xavier())
        preds = mod.predict(it)
        assert preds.shape == (110, 3)

    def test_forward_backward_update_manual(self):
        X, Y = _toy_problem(n=100)
        it = mx.io.NDArrayIter(X, Y, batch_size=20)
        mod = mx.mod.Module(_mlp_sym(), context=mx.cpu())
        mod.bind(it.provide_data, it.provide_label, for_training=True)
        mod.init_params(initializer=mx.initializer.Xavier())
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1})
        w0 = mod._exec.arg_dict["fc1_weight"].asnumpy().copy()
        batch = next(iter(it))
        mod.forward_backward(batch)
        mod.update()
        w1 = mod._exec.arg_dict["fc1_weight"].asnumpy()
        assert not np.allclose(w0, w1)

    def test_fixed_params_not_updated(self):
        X, Y = _toy_problem(n=100)
        it = mx.io.NDArrayIter(X, Y, batch_size=20)
        mod = mx.mod.Module(_mlp_sym(), context=mx.cpu(),
                            fixed_param_names=["fc1_weight"])
        mod.bind(it.provide_data, it.provide_label, for_training=True)
        mod.init_params(initializer=mx.initializer.Xavier())
        mod.init_optimizer(optimizer="sgd", optimizer_params={"learning_rate": 0.1})
        w0 = mod._exec.arg_dict["fc1_weight"].asnumpy().copy()
        batch = next(iter(it))
        mod.forward_backward(batch)
        mod.update()
        np.testing.assert_allclose(w0, mod._exec.arg_dict["fc1_weight"].asnumpy())


class TestBucketingModule:
    def test_buckets_share_weights(self):
        """Two seq-length buckets must train the same parameters (the
        BucketingModule shared-executor contract)."""
        def sym_gen(seq_len):
            data = sym.Variable("data")
            label = sym.Variable("softmax_label")
            net = sym.FullyConnected(data, num_hidden=8, name="fc1",
                                     flatten=True)
            net = sym.Activation(net, act_type="relu", name="a")
            net = sym.FullyConnected(net, num_hidden=2, name="fc2")
            net = sym.SoftmaxOutput(net, label=label, name="softmax",
                                    normalization="batch")
            return net, ("data",), ("softmax_label",)

        # same weight shapes across buckets: vary batch rather than feature
        mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=16)
        rng = np.random.RandomState(0)
        data16 = [mx.nd.array(rng.randn(16, 6).astype(np.float32))]
        label16 = [mx.nd.array(rng.randint(0, 2, (16,)).astype(np.float32))]
        data8 = [mx.nd.array(rng.randn(8, 6).astype(np.float32))]
        label8 = [mx.nd.array(rng.randint(0, 2, (8,)).astype(np.float32))]
        from incubator_mxnet_tpu.io import DataBatch, DataDesc
        b16 = DataBatch(data16, label16, bucket_key=16,
                        provide_data=[DataDesc("data", (16, 6))],
                        provide_label=[DataDesc("softmax_label", (16,))])
        b8 = DataBatch(data8, label8, bucket_key=8,
                       provide_data=[DataDesc("data", (8, 6))],
                       provide_label=[DataDesc("softmax_label", (8,))])

        mod.bind([DataDesc("data", (16, 6))], [DataDesc("softmax_label", (16,))])
        mod.init_params(initializer=mx.initializer.Xavier())
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.1})
        for batch in (b16, b8, b16, b8):
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
        arg_params, _ = mod.get_params()
        w_master = arg_params["fc1_weight"].asnumpy()
        w_bucket8 = mod._buckets[8]._exec.arg_dict["fc1_weight"].asnumpy()
        np.testing.assert_allclose(w_master, w_bucket8)


class TestReviewRegressions:
    def test_roll_over_defers_tail(self):
        """roll_over must not pad/double-count: the epoch tail rolls into
        the next epoch's first batch."""
        X = np.arange(10, dtype=np.float32).reshape(10, 1)
        it = mx.io.NDArrayIter(X, None, batch_size=4, last_batch_handle="roll_over")
        e1 = np.concatenate([b.data[0].asnumpy().ravel() for b in it])
        assert len(e1) == 8 and len(set(e1.tolist())) == 8  # no duplicates
        it.reset()
        e2 = np.concatenate([b.data[0].asnumpy().ravel() for b in it])
        assert len(e2) == 12  # 2 carried + 10 new → 3 full batches
        leftover = set(range(10)) - set(e1.tolist())
        assert leftover <= set(e2.tolist())

    def test_prefetch_reset_no_stale_batch(self):
        """reset() mid-epoch must not leak a pre-reset batch (review
        finding: the worker's blocked put landed a stale batch)."""
        X = np.arange(16, dtype=np.float32).reshape(16, 1)
        base = mx.io.NDArrayIter(X, None, batch_size=4)
        pf = mx.io.PrefetchingIter(base)
        first = pf.next().data[0].asnumpy().ravel()
        pf.reset()
        again = pf.next().data[0].asnumpy().ravel()
        np.testing.assert_array_equal(first, again)

    def test_roll_over_reset_before_consume_no_duplicates(self):
        """reset() before consuming any batch must not carry the whole
        order into the next epoch (advisor finding: every sample appeared
        twice after score(reset=True)-style immediate resets)."""
        X = np.arange(8, dtype=np.float32).reshape(8, 1)
        it = mx.io.NDArrayIter(X, None, batch_size=2, last_batch_handle="roll_over")
        it.reset()  # nothing consumed yet
        e = np.concatenate([b.data[0].asnumpy().ravel() for b in it])
        assert len(e) == 8, f"epoch yielded {len(e)} samples, expected 8"
        assert sorted(e.tolist()) == list(range(8))

    def test_roll_over_mid_epoch_reset_carries_exact_tail(self):
        """A mid-epoch reset must carry exactly the unconsumed tail: not
        the in-flight consumed batch (double-count), and not nothing
        (dropped samples)."""
        X = np.arange(8, dtype=np.float32).reshape(8, 1)
        it = mx.io.NDArrayIter(X, None, batch_size=2, last_batch_handle="roll_over")
        got = [it.next().data[0].asnumpy().ravel() for _ in range(2)]  # [0,1],[2,3]
        it.reset()
        e2 = np.concatenate([b.data[0].asnumpy().ravel() for b in it])
        # 4 carried (4..7) + 8 new = 12 samples, consumed exactly once each
        assert len(e2) == 12, len(e2)
        counts = {v: (e2 == v).sum() for v in range(8)}
        assert all(counts[v] == 2 for v in (4, 5, 6, 7)), counts
        assert all(counts[v] == 1 for v in (0, 1, 2, 3)), counts

    def test_prefetch_worker_exception_propagates(self):
        """A non-StopIteration error in the wrapped iterator must surface
        in the consumer, not hang it forever (advisor finding)."""

        class BoomIter(mx.io.DataIter):
            def __init__(self):
                super().__init__(batch_size=2)
                self.provide_data = [mx.io.DataDesc("data", (2, 1))]
                self.provide_label = []

            def next(self):
                raise RuntimeError("boom")

            def reset(self):
                pass

        pf = mx.io.PrefetchingIter(BoomIter())
        with pytest.raises(RuntimeError, match="boom"):
            pf.next()

    def test_optimizer_state_resume(self, tmp_path):
        X = np.random.RandomState(0).randn(40, 6).astype(np.float32)
        Y = (X.sum(axis=1) > 0).astype(np.float32)
        it = mx.io.NDArrayIter(X, Y, batch_size=20)
        mod = mx.mod.Module(_mlp_sym(hidden=8, k=2), context=mx.cpu())
        mod.fit(it, optimizer="adam", optimizer_params={"learning_rate": 0.01},
                num_epoch=2, initializer=mx.initializer.Xavier())
        prefix = str(tmp_path / "resume")
        mod.save_checkpoint(prefix, 2, save_optimizer_states=True)

        mod2 = mx.mod.Module.load(prefix, 2, load_optimizer_states=True)
        mod2.bind(it.provide_data, it.provide_label, for_training=True)
        mod2.init_params()
        mod2.init_optimizer(optimizer="adam", optimizer_params={"learning_rate": 0.01})
        # Adam second-moment state must survive the round trip (states are
        # keyed by parameter name so bucket modules can share them safely)
        assert mod2._updater_states, "optimizer states not restored"
        pname = mod._param_names[0]
        ref_state = mod._updater_states[pname]
        new_state = mod2._updater_states[pname]
        np.testing.assert_allclose(
            np.asarray(ref_state[0].asnumpy() if hasattr(ref_state[0], 'asnumpy') else ref_state[0]),
            np.asarray(new_state[0].asnumpy() if hasattr(new_state[0], 'asnumpy') else new_state[0]),
            rtol=1e-6)


def test_feedforward_legacy_api(tmp_path):
    """mx.model.FeedForward — the pre-Module wrapper ([U:python/mxnet/
    model.py]): fit on arrays, predict (ragged last batch), save/load."""
    import incubator_mxnet_tpu.symbol as S

    S.symbol._reset_naming()
    net = S.SoftmaxOutput(
        S.FullyConnected(S.var("data"), num_hidden=2, name="fc"),
        S.var("softmax_label"), name="softmax")
    rng = np.random.RandomState(0)
    X = rng.rand(150, 4).astype(np.float32)
    y = (X.sum(1) > 2).astype(np.float32)
    model = mx.model.FeedForward(net, num_epoch=8, optimizer="sgd",
                                 learning_rate=0.5, numpy_batch_size=32)
    model.fit(X, y)
    pred = model.predict(X)
    assert pred.shape == (150, 2)
    assert (pred.argmax(1) == y).mean() > 0.8

    model.save(str(tmp_path / "ff"), 8)
    m2 = mx.model.FeedForward.load(str(tmp_path / "ff"), 8)
    np.testing.assert_allclose(m2.predict(X), pred, rtol=1e-5)

    m3 = mx.model.FeedForward.create(net, X, y, num_epoch=2,
                                     learning_rate=0.5)
    assert m3.arg_params is not None
