"""Quantized collectives — the gradient-compression subsystem (ISSUE 14).

Covers the acceptance surface: exact bf16/int8 encode-decode round-trip
contracts, error-feedback residual carry, the per-parameter-group
opt-out (mixed buckets stay exact for opted-out groups), bucket keys
namespaced by codec id with the dist store's loud wire-agreement check,
the async-PS ``push_enc`` envelope (server accumulates decoded fp32),
SPMDTrainer's in-program quantized dp-allreduce (parity with the fp32
build, convergence of int8 + error feedback to fp32 final loss, zero
steady-state recompiles under ``MXNET_COMPILE_GUARD=raise``,
``step``/``step_bulk`` equivalence, residual persistence through
``save_states``/``load_states``), the comms byte counters + ``comm``
metrics provider, and a CI smoke of ``benchmark/opperf/collectives.py``.

ISSUE 19 adds the quantized ring collectives: the int4 packed codec
(wire bytes + host-path rejection), the explicit-hop ring allreduce
(numerics, the aggregate error-feedback invariant, D=1 bit-exactness
with the psum sandwich, zero steady-state recompiles), the fsdp-sharded
quantized reduce-scatter/all-gather build (convergence parity), and the
async-PS encoded pull leg (versioned envelope, loud codec-id/version
mismatch).
"""
import os
import socket

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import comm, gluon, profiler
from incubator_mxnet_tpu import kvstore as kv_mod
from incubator_mxnet_tpu.comm import compression as comp_mod
from incubator_mxnet_tpu.gluon import Parameter, nn
from incubator_mxnet_tpu.parallel import SPMDTrainer, make_mesh

nd = mx.nd


@pytest.fixture(autouse=True)
def fresh_counters():
    profiler.reset_counters()
    yield
    profiler.reset_counters()


def _c():
    return profiler.counters()


# ---------------------------------------------------------------------------
# codec round-trips
# ---------------------------------------------------------------------------


def test_bf16_roundtrip_matches_astype():
    import jax.numpy as jnp

    x = jnp.asarray(np.random.RandomState(0).randn(301).astype(np.float32))
    codec = comm.Bf16Codec()
    payload, resid = codec.encode(x)
    dec = codec.decode(payload, 301)
    ref = np.asarray(x).astype(jnp.bfloat16).astype(np.float32)
    np.testing.assert_array_equal(np.asarray(dec), ref)
    # residual is exactly the truncation error
    np.testing.assert_allclose(np.asarray(resid),
                               np.asarray(x) - ref, rtol=0, atol=0)


def test_int8_roundtrip_error_bounded_and_grid_exact():
    import jax.numpy as jnp

    rs = np.random.RandomState(1)
    x = rs.randn(1000).astype(np.float32) * 3.0
    codec = comm.Int8BlockCodec(block=128)
    payload, resid = codec.encode(jnp.asarray(x))
    dec = np.asarray(codec.decode(payload, 1000))
    scales = np.asarray(payload["scales"])
    # per-block error bound: half a quantization step
    bound = np.repeat(np.where(scales > 0, scales, 1.0), 128)[:1000]
    assert (np.abs(dec - x) <= bound / 2 + 1e-7).all()
    # residual == what the codec dropped (small fp reassociation slack:
    # the residual is computed inside the fused encode program)
    np.testing.assert_allclose(np.asarray(resid), x - dec,
                               rtol=1e-4, atol=1e-5)
    # values already on the quantization grid decode EXACTLY: pin the
    # block scale with a +/-127*s entry, put everything else on k*s
    s = 0.03125  # power of two: k*s is exact in fp32
    on_grid = (rs.randint(-127, 128, 256) * s).astype(np.float32)
    on_grid[0] = 127 * s
    big = comm.Int8BlockCodec(block=256)
    payload2, resid2 = big.encode(jnp.asarray(on_grid))
    np.testing.assert_array_equal(np.asarray(big.decode(payload2, 256)),
                                  on_grid)
    np.testing.assert_array_equal(np.asarray(resid2), np.zeros(256))


def test_int8_zero_block_safe():
    import jax.numpy as jnp

    codec = comm.Int8BlockCodec(block=4)
    x = jnp.zeros((8,), jnp.float32)
    payload, resid = codec.encode(x)
    np.testing.assert_array_equal(np.asarray(codec.decode(payload, 8)),
                                  np.zeros(8))
    np.testing.assert_array_equal(np.asarray(resid), np.zeros(8))


def test_codec_ids_roundtrip():
    assert comm.codec_from_id("bf16").id == "bf16"
    assert comm.codec_from_id("int8b512").block == 512
    assert comm.Int8BlockCodec(64).id == "int8b64"
    with pytest.raises(ValueError):
        comm.codec_from_id("int7")


def test_decode_np_matches_device_decode():
    import jax.numpy as jnp

    x = np.random.RandomState(2).randn(130).astype(np.float32)
    codec = comm.Int8BlockCodec(block=32)
    payload, _ = codec.encode(jnp.asarray(x))
    np_payload = {k: np.asarray(v) for k, v in payload.items()}
    np.testing.assert_allclose(
        comm.decode_np(codec.id, np_payload, 130),
        np.asarray(codec.decode(payload, 130)), atol=1e-6)
    bf = comm.Bf16Codec()
    payload, _ = bf.encode(jnp.asarray(x))
    np.testing.assert_array_equal(
        comm.decode_np("bf16", {"enc": np.asarray(payload["enc"])}, 130),
        np.asarray(bf.decode(payload, 130)))


# ---------------------------------------------------------------------------
# error feedback
# ---------------------------------------------------------------------------


def test_error_feedback_residual_carry():
    """k compensated pushes of the same gradient sum to ~k*g: the running
    error stays bounded by ONE quantization step instead of growing."""
    import jax.numpy as jnp

    g = np.random.RandomState(3).randn(256).astype(np.float32)
    codec = comm.Int8BlockCodec(block=64)
    fb = comm.ErrorFeedback()
    total = np.zeros_like(g)
    for _ in range(5):
        flat = fb.compensate("k", jnp.asarray(g))
        payload, resid = codec.encode(flat)
        fb.update("k", resid)
        total += np.asarray(codec.decode(payload, 256))
    scales = np.asarray(codec.local_scales(jnp.asarray(g)))
    bound = np.repeat(np.where(scales > 0, scales, 1.0), 64)[:256]
    assert (np.abs(total - 5 * g) <= bound + 1e-6).all()


def test_error_feedback_retain_and_shape_guard():
    import jax.numpy as jnp

    fb = comm.ErrorFeedback()
    fb.update("__grad_bucket__:0:int8b256:float32:0", jnp.zeros(4))
    fb.update("__grad_bucket__:1:int8b256:float32:0", jnp.zeros(4))
    fb.update("__grad_bucket__:0:bf16:float32:0", jnp.zeros(4))
    fb.retain("__grad_bucket__:1:int8b256:")
    assert list(fb.state_dict()) == ["__grad_bucket__:1:int8b256:float32:0"]
    # layout change under a reused key: residual dropped, not misapplied
    assert fb.get("__grad_bucket__:1:int8b256:float32:0",
                  jnp.zeros(8)) is None
    assert len(fb) == 0


def test_error_feedback_state_dict_roundtrip():
    import jax.numpy as jnp

    fb = comm.ErrorFeedback()
    fb.update("a", jnp.asarray(np.arange(4, dtype=np.float32)))
    fb2 = comm.ErrorFeedback()
    fb2.load_state_dict(fb.state_dict())
    out = fb2.compensate("a", jnp.zeros(4))
    np.testing.assert_array_equal(np.asarray(out),
                                  np.arange(4, dtype=np.float32))


# ---------------------------------------------------------------------------
# policy / opt-out resolution
# ---------------------------------------------------------------------------


def test_policy_env_resolution(monkeypatch):
    monkeypatch.delenv("MXNET_GRAD_COMPRESS", raising=False)
    assert comm.resolve_policy() is None
    monkeypatch.setenv("MXNET_GRAD_COMPRESS", "off")
    assert comm.resolve_policy() is None
    monkeypatch.setenv("MXNET_GRAD_COMPRESS", "bf16")
    pol = comm.resolve_policy()
    assert pol.id == "bf16" and pol.error_feedback is False
    monkeypatch.setenv("MXNET_GRAD_COMPRESS", "int8")
    monkeypatch.setenv("MXNET_GRAD_COMPRESS_BLOCK", "128")
    pol = comm.resolve_policy()
    assert pol.id == "int8b128" and pol.error_feedback is True
    monkeypatch.setenv("MXNET_GRAD_COMPRESS_EF", "0")
    assert comm.resolve_policy().error_feedback is False
    monkeypatch.setenv("MXNET_GRAD_COMPRESS", "int4")
    monkeypatch.delenv("MXNET_GRAD_COMPRESS_EF", raising=False)
    pol = comm.resolve_policy()
    assert pol.id == "int4b128" and pol.error_feedback is True
    assert isinstance(pol.codec, comm.Int4PackedCodec)
    # the exchange algorithm rides its own knob (default psum)
    assert pol.algo == "psum"
    monkeypatch.setenv("MXNET_GRAD_COMPRESS_ALGO", "ring")
    assert comm.resolve_policy().algo == "ring"
    monkeypatch.setenv("MXNET_GRAD_COMPRESS_ALGO", "butterfly")
    with pytest.raises(ValueError, match="butterfly"):
        comm.resolve_policy()
    monkeypatch.delenv("MXNET_GRAD_COMPRESS_ALGO", raising=False)
    with pytest.raises(ValueError, match="tree"):
        comm.CompressionPolicy(comm.Int8BlockCodec(), algo="tree")


def test_quantization_sensitive_groups(monkeypatch):
    from incubator_mxnet_tpu.optimizer.fused import quantization_sensitive

    for name in ("bn0_gamma", "bn0_beta", "dense1_bias", "ln_norm_weight",
                 "tok_embedding_weight", "batchnorm2_moving_mean"):
        assert quantization_sensitive(name)
    assert not quantization_sensitive("dense1_weight")
    pol = comm.CompressionPolicy(comm.Int8BlockCodec())
    assert pol.codec_for("dense1_weight") is not None
    assert pol.codec_for("dense1_bias") is None
    assert pol.codec_for(None) is not None   # no name info -> compress
    # env regex replaces the builtin classification
    monkeypatch.setenv("MXNET_GRAD_COMPRESS", "int8")
    monkeypatch.setenv("MXNET_GRAD_COMPRESS_SKIP", "dense1_")
    pol = comm.resolve_policy()
    assert pol.codec_for("dense1_weight") is None
    assert pol.codec_for("bn0_gamma") is not None


# ---------------------------------------------------------------------------
# bucketed pushpull wire
# ---------------------------------------------------------------------------


def _make_params(n, seed, shape=(16, 8)):
    rs = np.random.RandomState(seed)
    params = []
    for k in range(n):
        p = Parameter(f"p{k}_weight", shape=shape, dtype="float32")
        p.initialize()
        p.set_data(nd.array(rs.randn(*shape).astype(np.float32)))
        params.append(p)
    return params


def test_bucketed_pushpull_mixed_groups_exact_optout():
    params = _make_params(4, 0)
    pb = Parameter("p_bias", shape=(8,), dtype="float32")
    pb.initialize()
    pb.set_data(nd.array(np.random.RandomState(9).randn(8).astype(np.float32)))
    params.append(pb)
    kv = kv_mod.create("dist_sync")
    gvals = [np.random.RandomState(10 + i).randn(*p.shape).astype(np.float32)
             for i, p in enumerate(params)]
    for p, g in zip(params, gvals):
        p.grad()[:] = nd.array(g)
    pol = comm.CompressionPolicy(comm.Int8BlockCodec(block=64))
    fb = comm.ErrorFeedback()
    kv_mod.bucketed_pushpull(kv, [(i, p.grad()) for i, p in enumerate(params)],
                             names=[p.name for p in params],
                             compression=pol, feedback=fb)
    # opted-out group (bias) is BIT-exact; compressed groups are bounded
    np.testing.assert_array_equal(params[-1].grad().asnumpy(), gvals[-1])
    for p, g in zip(params[:-1], gvals[:-1]):
        assert np.abs(p.grad().asnumpy() - g).max() <= np.abs(g).max() / 100
    # two wire formats -> two buckets; bytes counted raw > wire
    assert _c()["allreduce_bucket"] == 2
    assert _c()["allreduce_bucket_params"] == 5
    assert _c()["comms_bytes_raw"] > _c()["comms_bytes_wire"] > 0
    # residual keyed by the full codec-namespaced bucket key (satellite:
    # codec id beside the membership epoch)
    (key,) = fb.state_dict().keys()
    assert key == "__grad_bucket__:0:int8b64:float32:0"


def test_bucketed_pushpull_codec_toggle_prunes_residuals():
    params = _make_params(2, 4)
    kv = kv_mod.create("dist_sync")
    fb = comm.ErrorFeedback()
    for codec in (comm.Int8BlockCodec(64), comm.Int8BlockCodec(32)):
        for p in params:
            p.grad()[:] = nd.array(np.ones(p.shape, np.float32))
        pol = comm.CompressionPolicy(codec)
        kv_mod.bucketed_pushpull(
            kv, [(i, p.grad()) for i, p in enumerate(params)],
            names=[p.name for p in params], compression=pol, feedback=fb)
    # only the CURRENT codec's residuals survive a toggle
    keys = list(fb.state_dict())
    assert keys and all(":int8b32:" in k for k in keys)


def test_bucketed_pushpull_fp32_counts_bytes_equal():
    params = _make_params(3, 5)
    kv = kv_mod.create("dist_sync")
    for p in params:
        p.grad()[:] = nd.array(np.ones(p.shape, np.float32))
    kv_mod.bucketed_pushpull(kv, [(i, p.grad()) for i, p in enumerate(params)])
    assert _c()["comms_bytes_raw"] == _c()["comms_bytes_wire"] > 0


def test_wire_agreement_check_raises_on_divergence(monkeypatch):
    kv = kv_mod.create("dist_sync")
    # single process: a no-op by contract
    kv.check_wire_agreement("__grad_bucket__:0:int8b256:float32:0")
    # simulate 2 processes whose key hashes disagree
    import jax

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(
        kv, "_allreduce",
        lambda arr, op="sum": np.asarray([int(arr[0]) + 7, int(arr[1])]))
    with pytest.raises(RuntimeError, match="wire-format mismatch"):
        kv.check_wire_agreement("__grad_bucket__:0:bf16:float32:0")
    # agreement passes — and is NOT cached: the check must re-run every
    # bucket so a peer that never changed its key still participates in
    # (and raises from) a toggling worker's mismatch
    calls = []

    def agree(arr, op="sum"):
        calls.append(op)
        return np.asarray(arr)

    monkeypatch.setattr(kv, "_allreduce", agree)
    kv.check_wire_agreement("__grad_bucket__:0:fp32:float32:1")
    kv.check_wire_agreement("__grad_bucket__:0:fp32:float32:1")
    assert len(calls) == 2


def test_trainer_dist_sync_env_policy_and_feedback_persistence(
        monkeypatch, tmp_path):
    monkeypatch.setenv("MXNET_GRAD_COMPRESS", "int8")
    params = _make_params(3, 6)
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1},
                       kvstore="dist_sync")
    for p in params:
        p.grad()[:] = nd.array(np.random.RandomState(1).randn(*p.shape)
                               .astype(np.float32))
    tr.allreduce_grads()
    assert tr._grad_feedback is not None and len(tr._grad_feedback)
    f = str(tmp_path / "states")
    tr.save_states(f)
    tr2 = gluon.Trainer(_make_params(3, 6), "sgd", {"learning_rate": 0.1},
                        kvstore="dist_sync")
    tr2.load_states(f)
    assert (tr2._grad_feedback.state_dict().keys()
            == tr._grad_feedback.state_dict().keys())
    # a snapshot with NO residuals clears live ones on restore — keeping
    # them would compensate the restored step with another trajectory's
    # quantization error
    tr3 = gluon.Trainer(_make_params(3, 6), "sgd", {"learning_rate": 0.1},
                        kvstore="dist_sync")
    f2 = str(tmp_path / "fresh_states")
    tr3.save_states(f2)   # never stepped: no grad_feedback in the payload
    tr2.load_states(f2)
    assert len(tr2._grad_feedback) == 0


# ---------------------------------------------------------------------------
# per-key compressed pushpull (non-bucketed dist path)
# ---------------------------------------------------------------------------


def test_dist_per_key_codec_compression():
    kv = kv_mod.create("dist_sync")
    kv.set_gradient_compression({"type": "int8", "block": 8})
    g = nd.array(np.random.RandomState(2).randn(4, 8).astype(np.float32))
    out = nd.zeros((4, 8))
    kv.pushpull("w", g, out=out)
    ref = g.asnumpy()
    assert np.abs(out.asnumpy() - ref).max() <= np.abs(ref).max() / 60
    assert kv._last_wire_dtype == "int8"
    assert not kv.supports_grad_bucketing()  # per-key residual semantics


# ---------------------------------------------------------------------------
# async PS: codec envelope, server accumulates decoded fp32
# ---------------------------------------------------------------------------


@pytest.fixture()
def async_store(monkeypatch):
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    monkeypatch.setenv("MXNET_ASYNC_PS_PORT", str(port))
    monkeypatch.setenv("DMLC_WORKER_ID", "0")
    monkeypatch.setenv("DMLC_NUM_WORKER", "1")
    from incubator_mxnet_tpu.kvstore import async_ps

    monkeypatch.setattr(async_ps, "_SERVER", None)
    kv = mx.kv.create("dist_async")
    yield kv
    kv.close()   # stops the heartbeat thread — leaked, it trips the
    # thread-leak teardown of every later test in the run
    kv._server.stop()


def test_async_push_enc_int8_with_error_feedback(async_store):
    kv = async_store
    kv.set_gradient_compression({"type": "int8", "block": 4})
    kv.init("w", nd.zeros((6,)))
    g = np.array([0.7, -0.9, 0.2, 0.0, 3.0, -0.1], np.float32)
    for k in range(1, 4):
        kv.push("w", nd.array(g))
        out = nd.zeros((6,))
        kv.pull("w", out=out)
        # server accumulates DECODED fp32; with error feedback the
        # running sum stays within one quantization step of k*g — plus
        # one more step for the encoded pull leg (the server's fp32
        # master re-quantizes per read, never accumulated)
        scale = 3.0 / 127.0  # the largest block's grid
        assert np.abs(out.asnumpy() - k * g).max() <= 2 * scale + 1e-6
    assert kv._last_wire_dtype == "int8"
    assert _c()["comms_bytes_raw"] > _c()["comms_bytes_wire"] > 0


def test_async_push_enc_bf16(async_store):
    kv = async_store
    kv.set_gradient_compression({"type": "bf16"})
    kv.init("w", nd.zeros((4,)))
    x = np.array([1.0, 2.5, -3.25, 0.001], np.float32)
    kv.push("w", nd.array(x))
    out = nd.zeros((4,))
    kv.pull("w", out=out)
    import jax.numpy as jnp

    ref = x.astype(jnp.bfloat16).astype(np.float32)
    np.testing.assert_array_equal(out.asnumpy(), ref)
    assert kv._last_wire_dtype == "bfloat16"


def test_async_int8_training_converges_to_fp32(async_store):
    """Async-PS convergence parity: server-side SGD driven by int8+EF
    pushes reaches the fp32 run's weights within quantization tolerance
    on a deterministic least-squares problem."""
    kv = async_store
    rs = np.random.RandomState(0)
    w_true = rs.randn(8).astype(np.float32)
    X = rs.randn(64, 8).astype(np.float32)
    y = X @ w_true

    def train(compressed):
        key = "w_c" if compressed else "w_f"
        if compressed:
            kv.set_gradient_compression({"type": "int8", "block": 8})
        else:
            kv._compression = None
        kv.init(key, nd.zeros((8,)))
        w = np.zeros(8, np.float32)
        for _ in range(60):
            grad = 2.0 / len(X) * X.T @ (X @ w - y)
            kv.push(key, nd.array(0.1 * grad))
            out = nd.zeros((8,))
            kv.pull(key, out=out)
            w = -out.asnumpy()  # accumulated (lr * grad) sum
        return w

    w_f = train(False)
    w_c = train(True)
    # both runs reach the same neighborhood of w_true
    assert np.abs(w_c - w_f).max() < 0.05
    assert np.linalg.norm(w_c - w_true) < 1.5 * np.linalg.norm(w_f - w_true) + 0.05


# ---------------------------------------------------------------------------
# SPMD quantized dp-allreduce
# ---------------------------------------------------------------------------


def _build_net(seed, features=16, hidden=32, classes=8):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(hidden, activation="relu"), nn.Dense(classes))
    net.initialize()
    net(nd.zeros((2, features)))
    return net


_LOSS = gluon.loss.SoftmaxCrossEntropyLoss()


def _spmd_pair(compression, seed=3, lr=0.1):
    ref = SPMDTrainer(_build_net(seed), _LOSS, "sgd", {"learning_rate": lr},
                      mesh=make_mesh())
    cmp_tr = SPMDTrainer(_build_net(seed), _LOSS, "sgd",
                         {"learning_rate": lr}, mesh=make_mesh(),
                         compression=compression)
    return ref, cmp_tr


def _batch(seed=0, batch=16, features=16, classes=8):
    rng = np.random.RandomState(seed)
    return (rng.randn(batch, features).astype(np.float32),
            rng.randint(0, classes, (batch,)).astype(np.float32))


@pytest.mark.parametrize("tier", ["bf16", "int8"])
def test_spmd_compressed_matches_fp32_losses(tier):
    ref, cmp_tr = _spmd_pair(tier)
    assert cmp_tr._comm_cfg is not None
    x, y = _batch()
    for _ in range(5):
        l0 = float(ref.step(nd.array(x), nd.array(y)).asnumpy())
        l1 = float(cmp_tr.step(nd.array(x), nd.array(y)).asnumpy())
        assert abs(l0 - l1) < 2e-3 * max(1.0, abs(l0))
    assert _c()["comms_bytes_raw"] > _c()["comms_bytes_wire"] > 0


def test_spmd_int8_convergence_parity():
    """dist_sync-tier convergence: int8 + error feedback over the dp=8
    quantized psum reaches the fp32 final loss within tolerance."""
    ref, cmp_tr = _spmd_pair("int8", lr=0.2)
    x, y = _batch(1)
    l0 = None
    for _ in range(40):
        lf = float(ref.step(nd.array(x), nd.array(y)).asnumpy())
        lc = float(cmp_tr.step(nd.array(x), nd.array(y)).asnumpy())
        l0 = lf if l0 is None else l0
    assert lc < 0.5 * l0       # actually trained
    assert abs(lc - lf) < 0.05 * max(lf, 0.1) + 0.02


def test_spmd_optout_slots_resolved():
    _, cmp_tr = _spmd_pair("int8")
    cfg = cmp_tr._comm_cfg
    names = [cmp_tr._params[cmp_tr._trainable_idx[s]].name
             for s in cfg["exact_slots"]]
    assert names and all("bias" in n for n in names)
    names_c = [cmp_tr._params[cmp_tr._trainable_idx[s]].name
               for s in cfg["comp_slots"]]
    assert names_c and all("weight" in n for n in names_c)


def test_spmd_all_optout_falls_back_to_plain_build():
    pol = comm.CompressionPolicy(comm.Int8BlockCodec(),
                                 skip=lambda name: True)
    tr = SPMDTrainer(_build_net(3), _LOSS, "sgd", {"learning_rate": 0.1},
                     mesh=make_mesh(), compression=pol)
    assert tr._comm_cfg is None and tr._comm_state is None


def test_spmd_unsupported_builds_warn_and_fall_back():
    # tp > 1 is still outside the compressed build's supported shape
    with pytest.warns(UserWarning, match="running uncompressed"):
        tr = SPMDTrainer(_build_net(3), _LOSS, "sgd", {"learning_rate": 0.1},
                         mesh=make_mesh(dp=4, tp=2), compression="int8")
    assert tr._comm_cfg is None
    x, y = _batch()
    tr.step(nd.array(x), nd.array(y))  # the fallback build still trains


def test_spmd_fsdp_sharded_builds_compressed():
    """fsdp-sharded parameters now COMPRESS (quantized reduce-scatter of
    grads + quantized all-gather of updated shards) instead of falling
    back — the PR 14 refusal is lifted for axis-0 'fsdp' shards."""
    from incubator_mxnet_tpu.parallel import fsdp_rules

    tr = SPMDTrainer(_build_net(3), _LOSS, "sgd", {"learning_rate": 0.1},
                     mesh=make_mesh(fsdp=2), rules=fsdp_rules(),
                     compression="int8")
    cfg = tr._comm_cfg
    assert cfg is not None and cfg["sharded"] and cfg["shard_ax"] == "fsdp"
    assert cfg["F"] == 2 and cfg["n"] == cfg["S"] * cfg["F"]
    assert cfg["comp_slots"] and cfg["hops"] > 0
    assert cfg["bytes_wire"] < cfg["bytes_raw"]


def test_spmd_zero_steady_state_recompiles(monkeypatch):
    monkeypatch.setenv("MXNET_COMPILE_GUARD", "raise")
    # fresh registry + disarmed guard: another test's trainer may have
    # armed the module-global guard against the same (site, signature)
    profiler.reset_compiles()
    profiler.disarm_compile_guard()
    try:
        _, cmp_tr = _spmd_pair("int8")
        x, y = _batch(2)
        cmp_tr.step(nd.array(x), nd.array(y))   # compile + arm
        base = _c()["recompile_steady_state"]
        for _ in range(3):
            cmp_tr.step(nd.array(x), nd.array(y))  # raise mode: any
            # steady-state recompile would throw CompileGuardError here
        assert _c()["recompile_steady_state"] == base
    finally:
        profiler.disarm_compile_guard()
        profiler.reset_compiles()


def test_spmd_step_bulk_matches_sequential_compressed():
    seq = SPMDTrainer(_build_net(5), _LOSS, "adam", {"learning_rate": 0.01},
                      mesh=make_mesh(), compression="int8")
    blk = SPMDTrainer(_build_net(5), _LOSS, "adam", {"learning_rate": 0.01},
                      mesh=make_mesh(), compression="int8")
    x, y = _batch(3)
    for _ in range(3):
        seq.step(nd.array(x), nd.array(y))
    blk.step_bulk(nd.array(x), nd.array(y), 3)
    for a, b in zip(seq._param_arrays, blk._param_arrays):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)
    # bulk carried the residual too
    np.testing.assert_allclose(np.asarray(seq._comm_state),
                               np.asarray(blk._comm_state),
                               rtol=2e-5, atol=2e-6)


def test_spmd_residual_persists_through_save_load(tmp_path):
    _, tr = _spmd_pair("int8")
    x, y = _batch(4)
    tr.step(nd.array(x), nd.array(y))
    resid = np.asarray(tr._comm_state)
    assert np.abs(resid).max() > 0
    f = str(tmp_path / "spmd_states")
    tr.save_states(f)
    _, tr2 = _spmd_pair("int8")
    tr2.load_states(f)
    np.testing.assert_array_equal(np.asarray(tr2._comm_state), resid)
    # layout mismatch: loud warning + fresh zeros, never a misapplied carry
    tr3 = SPMDTrainer(_build_net(3), _LOSS, "sgd", {"learning_rate": 0.1},
                      mesh=make_mesh(),
                      compression=comm.CompressionPolicy(
                          comm.Int8BlockCodec(block=32)))
    with pytest.warns(UserWarning, match="residuals"):
        tr3.load_states(f)
    assert np.abs(np.asarray(tr3._comm_state)).max() == 0
    # a residual-FREE snapshot (uncompressed trainer) also resets live
    # residuals: a restore must not carry another trajectory's error
    ref, tr4 = _spmd_pair("int8")
    tr4.step(nd.array(x), nd.array(y))
    assert np.abs(np.asarray(tr4._comm_state)).max() > 0
    f2 = str(tmp_path / "plain_states")
    ref.save_states(f2)
    tr4.load_states(f2)
    assert np.abs(np.asarray(tr4._comm_state)).max() == 0


def test_comm_metrics_provider_surfaces_bytes():
    _, cmp_tr = _spmd_pair("int8")
    x, y = _batch()
    cmp_tr.step(nd.array(x), nd.array(y))
    snap = profiler.metrics_snapshot()
    fields = snap["providers"]["comm"]
    assert fields["bytes_raw"] > fields["bytes_wire"] > 0
    assert fields["compression_ratio"] > 3.0
    text = profiler.render_prometheus()
    assert "mxnet_comm_bytes_wire" in text


def test_spmd_span_carries_payload_args(tmp_path):
    import json

    _, cmp_tr = _spmd_pair("int8")
    x, y = _batch()
    profiler.set_config(filename=str(tmp_path / "trace.json"))
    profiler.start()
    try:
        cmp_tr.step(nd.array(x), nd.array(y))
        path = profiler.dump()
    finally:
        profiler.set_config(filename="profile.json")
    with open(path) as f:
        doc = json.load(f)
    spans = [e for e in doc["traceEvents"]
             if isinstance(e, dict) and e.get("ph") == "B"
             and e.get("name") == "spmd.step"]
    assert spans
    args = spans[-1]["args"]
    assert args["bytes_raw"] > args["bytes_wire"] > 0
    assert args["codec"].startswith("int8b")


# ---------------------------------------------------------------------------
# quantized ring collectives + the int4 tier (ISSUE 19)
# ---------------------------------------------------------------------------


def test_int4_roundtrip_wire_and_host_rejection():
    import jax.numpy as jnp

    x = np.random.RandomState(7).randn(500).astype(np.float32) * 2.0
    codec = comm.Int4PackedCodec(block=64)
    assert codec.id == "int4b64"
    assert comm.codec_from_id("int4b64").block == 64
    payload, resid = codec.encode(jnp.asarray(x))
    assert np.asarray(payload["packed"]).dtype == np.uint8
    dec = np.asarray(codec.decode(payload, 500))
    # 4-bit grid: error bounded by half a step of the DECODED block scale
    scodes = np.asarray(payload["scodes"]).reshape(-1)
    scales = scodes.astype(np.float32) / 255.0 * float(payload["tmax"])
    bound = np.repeat(np.where(scales > 0, scales, 1.0), 64)[:500]
    assert (np.abs(dec - x) <= bound / 2 + 1e-6).all()
    np.testing.assert_allclose(np.asarray(resid), x - dec,
                               rtol=1e-4, atol=1e-5)
    # numpy decode (the async-PS server) matches the jitted decode
    np_payload = {k: np.asarray(v) for k, v in payload.items()}
    np.testing.assert_allclose(comm.decode_np("int4b64", np_payload, 500),
                               dec, atol=1e-6)
    # wire accounting: packed nibbles + uint8 scale codes + one fp32 tmax
    nb = -(-500 // 64)
    assert codec.wire_nbytes(500) == nb * 32 + nb + 4
    assert 4 * 500 / codec.wire_nbytes(500) > 6.0
    # the host bucket wire has no linear sum for packed nibbles: rejected
    with pytest.raises(TypeError, match="no wire protocol"):
        comm.bucket_allreduce(codec, jnp.asarray(x), lambda a, op: a)


@pytest.mark.parametrize("tier", ["int8b64", "int4b64"])
def test_ring_allreduce_numerics_and_ef_invariant(tier):
    """The explicit-hop ring allreduce sums the per-device buckets, and
    the per-device residuals sum EXACTLY to the dropped error
    (exact − delivered) — the aggregate EF invariant."""
    from incubator_mxnet_tpu.comm import ring

    codec = comm.codec_from_id(tier)
    n = 640
    x = np.random.RandomState(11).randn(n).astype(np.float32)
    out, resid = ring.ring_allreduce_sharded(
        codec, np.asarray(x), make_mesh(), axis_names=("dp",), algo="ring")
    out, resid = np.asarray(out), np.asarray(resid)
    exact = 8.0 * x  # replicated input: every device contributes x
    step = 127.0 if tier.startswith("int8") else 7.0
    assert np.abs(out - exact).max() <= 16 * np.abs(exact).max() / step
    np.testing.assert_allclose(resid.reshape(8, n).sum(axis=0),
                               exact - out, rtol=2e-4, atol=2e-4)
    # static plan matches what the trace layers report: 2(D-1) hops of
    # one encoded chunk each
    hops, bytes_hop = ring.hop_plan(codec, n, 8)
    assert hops == 14
    assert bytes_hop == codec.wire_nbytes(ring._ring_chunk(codec, n, 8))


def test_ring_psum_bitexact_at_world_one():
    """D=1 degenerate form: the ring is a local encode/decode roundtrip,
    bit-exact with the psum sandwich (same grid helpers at both ends)."""
    import jax

    from incubator_mxnet_tpu.comm import ring

    mesh1 = make_mesh(devices=jax.devices()[:1])
    x = np.random.RandomState(13).randn(300).astype(np.float32)
    for codec in (comm.Int8BlockCodec(64), comm.Int4PackedCodec(64)):
        a, ra = ring.ring_allreduce_sharded(codec, np.asarray(x), mesh1,
                                            axis_names=("dp",), algo="ring")
        b, rb = ring.ring_allreduce_sharded(codec, np.asarray(x), mesh1,
                                            axis_names=("dp",), algo="psum")
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(np.asarray(ra), np.asarray(rb))
    assert ring.hop_plan(comm.Int8BlockCodec(64), 300, 1) == (0, 0)


def test_ring_rs_ag_roundtrip():
    """Sharded-group exchange: quantized reduce-scatter then quantized
    all-gather of the reduced shards recovers the cross-device sum within
    the codec grid."""
    from incubator_mxnet_tpu.comm import ring

    codec = comm.Int8BlockCodec(32)
    n = 512  # divisible by the axis size
    x = np.random.RandomState(17).randn(n).astype(np.float32)
    gathered, resid = ring.ring_rs_ag_sharded(
        codec, np.asarray(x), make_mesh(fsdp=8), axis_name="fsdp")
    gathered = np.asarray(gathered)
    exact = 8.0 * x
    assert np.abs(gathered - exact).max() <= np.abs(exact).max() / 10
    assert np.asarray(resid).shape == (8 * n,)
    hops, bytes_hop = ring.rs_ag_hop_plan(codec, n, 8)
    assert hops == 14 and bytes_hop == codec.wire_nbytes(n // 8)


def test_spmd_ring_matches_fp32_losses_and_counts_hops():
    pol = comm.CompressionPolicy(comm.Int8BlockCodec(), algo="ring")
    ref, cmp_tr = _spmd_pair(pol)
    cfg = cmp_tr._comm_cfg
    assert cfg["algo"] == "ring" and cfg["hops"] == 14 > 0
    assert cfg["bytes_hop"] > 0
    x, y = _batch()
    for _ in range(5):
        l0 = float(ref.step(nd.array(x), nd.array(y)).asnumpy())
        l1 = float(cmp_tr.step(nd.array(x), nd.array(y)).asnumpy())
        assert abs(l0 - l1) < 5e-3 * max(1.0, abs(l0))
    # 2(D-1) encoded ppermute hops per step ride the counter
    assert _c()["comms_ring_hops"] == 5 * cfg["hops"]
    assert _c()["comms_bytes_raw"] > _c()["comms_bytes_wire"] > 0


def test_spmd_ring_zero_steady_state_recompiles(monkeypatch):
    monkeypatch.setenv("MXNET_COMPILE_GUARD", "raise")
    profiler.reset_compiles()
    profiler.disarm_compile_guard()
    try:
        pol = comm.CompressionPolicy(comm.Int8BlockCodec(), algo="ring")
        _, cmp_tr = _spmd_pair(pol)
        x, y = _batch(2)
        cmp_tr.step(nd.array(x), nd.array(y))   # compile + arm
        base = _c()["recompile_steady_state"]
        for _ in range(3):
            cmp_tr.step(nd.array(x), nd.array(y))
        assert _c()["recompile_steady_state"] == base
    finally:
        profiler.disarm_compile_guard()
        profiler.reset_compiles()


def test_spmd_fsdp_int8_convergence_parity():
    """The sharded compressed build (quantized RS of grads + quantized AG
    of updated shards, int8 + error feedback) converges to the fp32
    fsdp run's loss within the PR 14 tolerance."""
    from incubator_mxnet_tpu.parallel import fsdp_rules

    def mk(compression):
        return SPMDTrainer(_build_net(3), _LOSS, "sgd",
                           {"learning_rate": 0.2}, mesh=make_mesh(fsdp=2),
                           rules=fsdp_rules(), compression=compression)

    ref, cmp_tr = mk(None), mk("int8")
    assert cmp_tr._comm_cfg["sharded"]
    x, y = _batch(1)
    l0 = None
    for _ in range(40):
        lf = float(ref.step(nd.array(x), nd.array(y)).asnumpy())
        lc = float(cmp_tr.step(nd.array(x), nd.array(y)).asnumpy())
        l0 = lf if l0 is None else l0
    assert lc < 0.5 * l0       # actually trained
    assert abs(lc - lf) < 0.05 * max(lf, 0.1) + 0.02
    assert _c()["comms_ring_hops"] > 0
    assert _c()["comms_bytes_raw"] > _c()["comms_bytes_wire"] > 0


def test_async_pull_enc_int4(async_store):
    kv = async_store
    kv.set_gradient_compression({"type": "int4", "block": 4})
    kv.init("w", nd.zeros((6,)))
    g = np.array([0.7, -0.9, 0.2, 0.0, 3.0, -0.1], np.float32)
    kv.push("w", nd.array(g))
    out = nd.zeros((6,))
    kv.pull("w", out=out)
    # one 4-bit push quantization + one 4-bit pull quantization
    assert np.abs(out.asnumpy() - g).max() <= 2 * 3.0 / 7 + 1e-5
    assert kv._last_wire_dtype == "uint8"  # packed nibbles on the wire


def test_async_pull_enc_mismatches_fail_loudly(async_store):
    from incubator_mxnet_tpu.kvstore.async_ps import PSProtocolError

    kv = async_store
    kv.set_gradient_compression({"type": "int8", "block": 4})
    kv.init("w", nd.zeros((4,)))
    kv.push("w", nd.array(np.ones(4, np.float32)))
    # codec id the server cannot encode: named protocol error, not a
    # silent mis-decode (mixed old-server/new-client deployment)
    with pytest.raises(PSProtocolError, match="codec-id mismatch"):
        kv._client.request("pull_enc", "w", "nosuchcodec99", 1)
    # envelope version drift: the versioned pull leg rejects loudly too
    with pytest.raises(PSProtocolError, match="v99"):
        kv._client.request("pull_enc", "w", "int8b4",
                           comp_mod.PULL_ENC_WIRE_VERSION + 98)
    # the store itself still works after the rejected probes
    out = nd.zeros((4,))
    kv.pull("w", out=out)
    assert np.abs(out.asnumpy() - 1.0).max() <= 2 * 1.0 / 127 + 1e-6


# ---------------------------------------------------------------------------
# benchmark smoke
# ---------------------------------------------------------------------------


def test_collectives_benchmark_smoke():
    """Tier-1-adjacent smoke of benchmark/opperf/collectives.py: tiny
    sizes, proves the harness runs end-to-end and meets the >=3.5x int8
    byte acceptance on both paths (the timing numbers come from the full
    run, not here)."""
    import importlib.util

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmark", "opperf", "collectives.py")
    spec = importlib.util.spec_from_file_location("opperf_collectives", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    line = mod.run(n_params=8, shape=(32, 16), batch=16, hidden=64,
                   iters=1, warmup=1, repeats=1)
    assert line["bytes_acceptance"]
    assert line["post_warmup_recompiles"] == 0
    assert line["int8_byte_ratio"]["pushpull_int8"] >= 3.5
    assert line["int8_byte_ratio"]["spmd_int8"] >= 3.5
