"""Monitor + visualization (parity: [U:python/mxnet/monitor.py],
[U:python/mxnet/visualization.py])."""
import numpy as np

import incubator_mxnet_tpu as mx
import incubator_mxnet_tpu.symbol as S
from incubator_mxnet_tpu import gluon


class TestMonitor:
    def test_block_outputs_collected_on_interval(self):
        mx.random.seed(0)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(4))
        net.initialize()
        net(mx.nd.zeros((2, 6)))

        mon = mx.Monitor(interval=2, pattern=".*")
        mon.install(net)
        x = mx.nd.ones((2, 6))
        stats = []
        for _ in range(4):
            mon.tic()
            net(x)
            stats.append(mon.toc())
        # interval=2: steps 0 and 2 collect, 1 and 3 don't
        assert len(stats[0]) > 0 and len(stats[2]) > 0
        assert stats[1] == [] and stats[3] == []
        names = [n for _, n, _ in stats[0]]
        assert any("output" in n for n in names)
        mon.uninstall()
        mon.tic()
        net(x)
        assert mon.toc() == []  # hooks removed

    def test_monitor_all_includes_params_and_grads(self):
        from incubator_mxnet_tpu import autograd

        net = gluon.nn.Dense(3)
        net.initialize()
        net(mx.nd.zeros((1, 4)))
        mon = mx.Monitor(interval=1, monitor_all=True, sort=True)
        mon.install(net)
        mon.tic()
        with autograd.record():
            out = net(mx.nd.ones((2, 4)))
            out.sum().backward()
        res = mon.toc()
        names = [n for _, n, _ in res]
        assert any(n.endswith("weight") for n in names)
        assert any(n.endswith("weight_grad") for n in names)


class TestVisualization:
    def _sym(self):
        S.symbol._reset_naming()
        data = S.var("data")
        fc = S.FullyConnected(data, num_hidden=8, name="fc1")
        act = S.Activation(fc, act_type="relu", name="relu1")
        return S.FullyConnected(act, num_hidden=2, name="fc2")

    def test_print_summary_counts_params(self, capsys):
        sym = self._sym()
        total = mx.viz.print_summary(sym, shape={"data": (1, 4)})
        out = capsys.readouterr().out
        # fc1: 4*8+8 = 40; fc2: 8*2+2 = 18
        assert total == 58
        assert "fc1 (FullyConnected)" in out and "Total params: 58" in out

    def test_plot_network_dot(self):
        dot = mx.viz.plot_network(self._sym(), shape={"data": (1, 4)})
        src = dot if isinstance(dot, str) else dot.source
        assert src.startswith("digraph")
        assert "fc1" in src and "->" in src
        # hidden weight variables are not drawn
        assert "fc1_weight" not in src

    def test_hybridized_net_does_not_crash(self):
        """Hooks must skip tracer values inside hybridize traces."""
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(4))
        net.initialize()
        net(mx.nd.zeros((1, 3)))
        net.hybridize()
        mon = mx.Monitor(interval=1)
        mon.install(net)
        mon.tic()
        out = net(mx.nd.ones((2, 3)))  # traces + executes without crashing
        assert out.shape == (2, 4)
        mon.toc()

    def test_name_pattern_and_leaf_block(self):
        """Patterns match block NAMES (dense0 style), and a childless block
        gets hooked itself."""
        d = gluon.nn.Dense(3)
        d.initialize()
        d(mx.nd.zeros((1, 2)))
        mon = mx.Monitor(interval=1, pattern="dense.*")
        mon.install(d)
        mon.tic()
        d(mx.nd.ones((1, 2)))
        rows = mon.toc()
        assert rows and rows[0][1].startswith("dense")

    def test_uninstall_stops_monitor_all(self):
        net = gluon.nn.Dense(3)
        net.initialize()
        net(mx.nd.zeros((1, 2)))
        mon = mx.Monitor(interval=1, monitor_all=True)
        mon.install(net)
        mon.uninstall()
        mon.tic()
        net(mx.nd.ones((1, 2)))
        assert mon.toc() == []
