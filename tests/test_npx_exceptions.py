"""mx.npx namespace + error-path handling.

Parity: the deep-numpy npx surface ([U:python/mxnet/numpy_extension/])
and [U:tests/python/unittest/test_exc_handling.py]'s discipline: failures
must surface as clean Python exceptions at the call site, not backend
crashes."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd


class TestNpx:
    def test_snake_case_nn_ops(self):
        x = mx.nd.array(np.array([[-1.0, 2.0]], np.float32))
        np.testing.assert_allclose(mx.npx.relu(x).asnumpy(), [[0, 2]])
        s = mx.npx.softmax(x).asnumpy()
        np.testing.assert_allclose(s.sum(axis=-1), [1.0], rtol=1e-6)
        w = mx.nd.ones((3, 2))
        out = mx.npx.fully_connected(x, w, None, num_hidden=3, no_bias=True)
        np.testing.assert_allclose(out.asnumpy(), [[1.0, 1.0, 1.0]])

    def test_batch_norm_alias(self):
        x = mx.nd.random.normal(shape=(2, 3, 4, 4))
        g, b = mx.nd.ones((3,)), mx.nd.zeros((3,))
        mm, mv = mx.nd.zeros((3,)), mx.nd.ones((3,))
        out = mx.npx.batch_norm(x, g, b, mm, mv)
        o = out[0] if isinstance(out, list) else out
        assert o.shape == (2, 3, 4, 4)

    def test_set_np_reexported(self):
        assert callable(mx.npx.set_np) and callable(mx.npx.reset_np)

    def test_unknown_op_attribute_error(self):
        with pytest.raises(AttributeError, match="npx has no op"):
            mx.npx.definitely_not_an_op

    def test_autograd_flows_through_npx(self):
        x = mx.nd.array(np.array([1.0, -2.0], np.float32))
        x.attach_grad()
        with autograd.record():
            y = mx.npx.relu(x).sum()
        y.backward()
        np.testing.assert_allclose(x.grad.asnumpy(), [1.0, 0.0])


class TestExceptionHandling:
    def test_unknown_nd_op(self):
        with pytest.raises(AttributeError):
            mx.nd.this_op_does_not_exist

    def test_asscalar_on_non_scalar(self):
        with pytest.raises((ValueError, TypeError)):
            mx.nd.ones((2, 2)).asscalar()

    def test_backward_off_tape(self):
        x = mx.nd.ones((2,))
        x.attach_grad()
        y = x * 2  # no record scope
        with pytest.raises((RuntimeError, ValueError)):
            y.backward()

    def test_bool_of_multielement_array(self):
        with pytest.raises((ValueError, TypeError)):
            bool(mx.nd.ones((3,)))

    def test_shape_mismatch_is_pythonic(self):
        with pytest.raises(Exception) as ei:
            mx.nd.dot(mx.nd.ones((2, 3)), mx.nd.ones((4, 5))).asnumpy()
        assert "3" in str(ei.value) or "dimension" in str(ei.value).lower() \
            or "shape" in str(ei.value).lower() or "contract" in str(ei.value).lower()

    def test_higher_order_grad_functional(self):
        """grad-of-grad via the functional surface (the reference's
        test_higher_order_grad coverage; our tape is first-order only and
        says so, the functional path goes all the way)."""
        import jax

        from incubator_mxnet_tpu.ops.registry import get_op

        tanh = get_op("tanh").fn
        f = lambda x: tanh(x).sum()
        x = np.float32(0.7)
        d1 = jax.grad(f)(x)
        d2 = jax.grad(jax.grad(f))(x)
        t = np.tanh(0.7)
        np.testing.assert_allclose(d1, 1 - t ** 2, rtol=1e-6)
        np.testing.assert_allclose(d2, -2 * t * (1 - t ** 2), rtol=1e-5)

    def test_npx_out_kwarg(self):
        x = mx.nd.array(np.array([-1.0, 2.0], np.float32))
        out = mx.nd.zeros((2,))
        res = mx.npx.relu(x, out=out)
        assert res is out
        np.testing.assert_allclose(out.asnumpy(), [0.0, 2.0])
