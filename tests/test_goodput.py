"""Goodput ledger (ISSUE 20): every second of a run accounted, exclusively.

Unit coverage of the run-scoped wall-clock decomposition in
``profiler`` — bucket exclusivity (the buckets sum to wall by
construction), downtime attribution, pause/resume wall semantics,
cluster aggregation naming the worst rank, and the metrics-provider /
Prometheus / trace-dump export surfaces — plus THE acceptance: a
supervised 2-proc dist_sync run with one injected SIGKILL restart and
one injected data stall, where the restart gap and the stall land in
their own buckets and the buckets sum to wall within 5%.
"""
import json
import os
import signal
import subprocess
import sys
import time

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SUPERVISE = os.path.join(ROOT, "tools", "supervise.py")

from incubator_mxnet_tpu import profiler


@pytest.fixture
def prof(tmp_path):
    """Armed profiler with a FRESH goodput ledger; restores + re-zeroes
    on exit so the run-scoped ledger never leaks across tests."""
    profiler.stop()
    profiler.set_config(filename=str(tmp_path / "trace.json"))
    profiler.start()
    profiler.reset_goodput()
    yield profiler
    profiler.stop()
    profiler.reset_goodput()


def _span(name, cat, dur):
    """Record a completed span of ``dur`` seconds ending now (the span
    recorder clamps t0 to the arm time, so keep durations < the armed
    window)."""
    now = time.perf_counter()
    profiler.record_span(name, cat, now - dur, now)


class TestLedgerExclusivity:
    def test_buckets_sum_to_wall_and_land_exclusively(self, prof):
        time.sleep(0.12)
        _span("io.wait", "io", 0.05)            # -> data_wait
        _span("kvstore.pushpull", "comms", 0.03)  # -> comm
        _span("elastic.snapshot", "elastic", 0.02)  # -> checkpoint
        snap = profiler.goodput_snapshot()
        b = snap["buckets_s"]
        # exclusivity invariant: compute is the clamped residual, so the
        # buckets sum to wall (per-bucket 6dp rounding leaves ~1e-5)
        assert sum(b.values()) == pytest.approx(snap["wall_s"], abs=1e-4)
        assert b["data_wait"] == pytest.approx(0.05, abs=0.02)
        assert b["comm"] == pytest.approx(0.03, abs=0.02)
        assert b["checkpoint"] == pytest.approx(0.02, abs=0.02)
        assert b["compute"] > 0
        assert 0 < snap["goodput"] <= 1
        assert snap["overhead_s"] == pytest.approx(
            snap["wall_s"] - b["compute"], abs=1e-4)

    def test_off_thread_spans_do_not_bill(self, prof):
        import threading

        def off_thread():
            _span("io.wait", "io", 0.05)

        t = threading.Thread(target=off_thread)
        t.start()
        t.join()
        assert profiler.goodput_snapshot()["buckets_s"]["data_wait"] == 0

    def test_parent_pushpull_is_not_double_billed(self, prof):
        # kvstore.bucketed_pushpull is the PARENT of per-bucket pushpull
        # legs — only the leaves bill, or comm would double-count
        _span("kvstore.bucketed_pushpull", "comms", 0.5)
        assert profiler.goodput_snapshot()["buckets_s"]["comm"] == 0


class TestDowntime:
    def test_downtime_lands_in_bucket_and_grows_wall(self, prof):
        w0 = profiler.goodput_snapshot()["wall_s"]
        profiler.record_downtime(0.25, "elastic_restart")
        snap = profiler.goodput_snapshot()
        assert snap["buckets_s"]["downtime"] == pytest.approx(0.25)
        # downtime happened while the process did not exist: wall grows
        # by the same amount (the buckets-sum-to-wall invariant)
        assert snap["wall_s"] >= w0 + 0.25
        assert snap["downtime_detail"]["elastic_restart"] == pytest.approx(0.25)
        assert ["downtime", 0.25] in snap["top_overhead"]
        assert sum(snap["buckets_s"].values()) == pytest.approx(
            snap["wall_s"], abs=1e-4)

    def test_nonpositive_downtime_is_a_noop(self, prof):
        before = profiler.counters()["goodput_downtime_ms"]
        profiler.record_downtime(0.0)
        profiler.record_downtime(-5.0)
        assert profiler.goodput_snapshot()["buckets_s"]["downtime"] == 0
        assert profiler.counters()["goodput_downtime_ms"] == before

    def test_downtime_counter_tracks_ms(self, prof):
        before = profiler.counters()["goodput_downtime_ms"]
        profiler.record_downtime(0.125, "elastic_restart")
        assert profiler.counters()["goodput_downtime_ms"] == before + 125


class TestPauseResume:
    def test_wall_is_monotone_and_freezes_while_paused(self, prof):
        time.sleep(0.02)
        w1 = profiler.goodput_snapshot()["wall_s"]
        profiler.pause()
        w2 = profiler.goodput_snapshot()["wall_s"]
        time.sleep(0.06)
        w3 = profiler.goodput_snapshot()["wall_s"]
        assert w1 <= w2  # monotone
        # frozen: the pause gap must NOT be billed (it would otherwise
        # inflate compute — nothing observed the process meanwhile)
        assert w3 == pytest.approx(w2, abs=5e-3)
        profiler.resume()
        time.sleep(0.02)
        w4 = profiler.goodput_snapshot()["wall_s"]
        assert w4 > w3
        assert w4 - w3 < 0.06  # the paused 60 ms never entered the wall

    def test_start_does_not_reset_the_run_ledger(self, prof, tmp_path):
        profiler.record_downtime(0.2, "elastic_restart")
        profiler.stop()
        profiler.set_config(filename=str(tmp_path / "trace2.json"))
        profiler.start()   # fresh SPAN session — same RUN ledger
        snap = profiler.goodput_snapshot()
        assert snap["buckets_s"]["downtime"] == pytest.approx(0.2)


class TestClusterAggregation:
    def _peer(self, rank, wall, compute, **buckets):
        g = {"wall_s": wall, "goodput": compute / wall,
             "compute_s": compute}
        g.update({f"{k}_s": v for k, v in buckets.items()})
        return {"schema": 1, "rank": rank, "pid": 990000 + rank, "seq": 1,
                "host": f"peer{rank}", "providers": {"goodput": g}}

    def test_worst_rank_and_its_bucket_are_named(self, prof):
        time.sleep(0.05)
        try:
            profiler.publish_peer_metrics(
                self._peer(1, 10.0, 9.0, comm=1.0))
            profiler.publish_peer_metrics(
                self._peer(2, 10.0, 2.0, comm=1.0, downtime=7.0))
            agg = profiler.cluster_goodput()
            assert agg["ranks"] == 3   # local + two peers
            assert agg["worst"]["rank"] == 2
            assert agg["worst"]["bucket"] == "downtime"
            assert agg["worst"]["bucket_s"] == pytest.approx(7.0)
            # job goodput is wall-weighted, so the straggler drags it
            assert agg["goodput"] < 0.75
        finally:
            profiler.forget_peer_metrics(1)
            profiler.forget_peer_metrics(2)

    def test_none_when_no_rank_has_wall(self):
        profiler.stop()
        profiler.reset_goodput()
        assert profiler.cluster_goodput() is None


class TestExportSurfaces:
    def test_provider_rides_metrics_snapshot_and_prometheus(self, prof):
        time.sleep(0.02)
        snap = profiler.metrics_snapshot()
        g = snap["providers"]["goodput"]
        for key in ("wall_s", "goodput", "compute_s", "data_wait_s",
                    "downtime_s"):
            assert key in g, key
        assert g["wall_s"] > 0
        text = profiler.render_prometheus()
        assert "mxnet_goodput_wall_s" in text
        assert "mxnet_goodput_compute_s" in text

    def test_snapshot_roundtrips_json_and_rides_dump(self, prof, tmp_path):
        _span("io.wait", "io", 0.01)
        snap = json.loads(json.dumps(profiler.goodput_snapshot()))
        assert snap["schema"] == 1
        assert set(snap["buckets_s"]) == set(profiler._GOODPUT_BUCKETS)
        profiler.stop()
        profiler.dump()
        with open(str(tmp_path / "trace.json")) as f:
            doc = json.load(f)
        gp = doc["otherData"]["goodput"]
        assert gp["schema"] == 1 and gp["buckets_s"]["data_wait"] > 0

    def test_snapshot_counter_counts_captures(self, prof):
        before = profiler.counters()["goodput_snapshot"]
        profiler.goodput_snapshot()
        profiler.goodput_snapshot()
        assert profiler.counters()["goodput_snapshot"] == before + 2


# ---------------------------------------------------------------------------
# THE acceptance: supervised 2-proc run, one SIGKILL restart + one data
# stall — every second lands in its bucket
# ---------------------------------------------------------------------------


def _subproc_env(**extra):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("MXNET_FAULT_SPEC", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.update({k: str(v) for k, v in extra.items()})
    return env


@pytest.mark.slow
def test_goodput_elastic_acceptance(tmp_path):
    """A 2-proc dist_sync folded run is SIGKILL'd on rank 1 at step 3
    (one supervisor restart) and rank 0 stalls 0.4 s on data at step 5.
    Each relaunched rank's ledger must (a) sum its buckets to wall
    within 5%, (b) carry the supervisor-measured restart gap in the
    ``downtime`` bucket under the ``elastic_restart`` reason, matching
    the run manifest, and (c) show the stall in ``data_wait`` on the
    stalled rank ONLY."""
    stall_s = 0.4
    manifest_path = str(tmp_path / "manifest.json")
    prefix = str(tmp_path / "run" / "ck")
    os.makedirs(os.path.dirname(prefix), exist_ok=True)
    env = _subproc_env(
        MXNET_COMPILE_WARMUP_STEPS="3", MXNET_COMPILE_GUARD="raise",
        MXNET_ELASTIC_BACKOFF_S="0.2", MXNET_FAULT_SEED="0",
        MXNET_FAULT_SPEC="proc.kill_rank:n=1:rank=1:at=3:gen=0",
        MXNET_TEST_STALL_S=str(stall_s), MXNET_TEST_STALL_AT="5",
        MXNET_TEST_STALL_RANK="0",
    )
    proc = subprocess.run(
        [sys.executable, SUPERVISE, "-n", "2", "--manifest", manifest_path,
         sys.executable, os.path.join(ROOT, "tests", "goodput_worker.py"),
         prefix],
        env=env, capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stderr[-3000:]
    restarts = [l for l in proc.stderr.splitlines()
                if l.startswith("ELASTIC_RESTART ")]
    assert len(restarts) == 1, proc.stderr[-3000:]
    rep = json.loads(restarts[0].split(" ", 1)[1])
    assert rep["reason"] == "rank_exit" and rep["rank"] == 1
    assert rep["exit_code"] == -signal.SIGKILL

    # the machine-readable run manifest tells the same story
    with open(manifest_path) as f:
        manifest = json.load(f)
    assert manifest["schema"] == 1 and manifest["final"] == "complete"
    assert manifest["restarts"] == 1
    assert len(manifest["generations"]) == 2
    assert manifest["generations"][0]["exit_cause"]["reason"] == "rank_exit"
    assert manifest["generations"][1]["exit_cause"]["reason"] == "clean"
    assert manifest["total_downtime_s"] >= 0.2   # at least the backoff

    # final-generation ledgers, one per rank
    snaps = {}
    for line in proc.stdout.splitlines():
        if line.startswith("GOODPUT_SNAPSHOT "):
            _, _, rank, payload = line.split(" ", 3)
            snaps[int(rank)] = json.loads(payload)
    assert sorted(snaps) == [0, 1], proc.stdout[-3000:]

    for rank, snap in snaps.items():
        b = snap["buckets_s"]
        # (a) every second accounted: buckets sum to wall within 5%
        assert sum(b.values()) == pytest.approx(
            snap["wall_s"], rel=0.05, abs=1e-4), (rank, snap)
        # (b) the restart gap landed in downtime, reason elastic_restart,
        # and equals what the supervisor measured into the manifest
        assert b["downtime"] == pytest.approx(
            manifest["total_downtime_s"], abs=0.05), (rank, snap)
        assert snap["downtime_detail"]["elastic_restart"] == pytest.approx(
            manifest["total_downtime_s"], abs=0.05)
    # (c) the stall is attributed to data_wait on the stalled rank only
    assert snaps[0]["buckets_s"]["data_wait"] >= stall_s * 0.9, snaps[0]
    assert snaps[1]["buckets_s"]["data_wait"] < 0.1, snaps[1]
