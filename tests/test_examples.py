"""Example-script smoke tier (SURVEY §4 'Tutorials/docs tests' analog:
the reference CI executes its tutorials; here every example/ script runs
end-to-end at a tiny config in a subprocess so the documented entry
points cannot rot)."""
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, *args, timeout=240):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)  # scripts that need a mesh self-provision
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "example", script), *args],
        env=env, capture_output=True, text=True, timeout=timeout,
    )
    assert proc.returncode == 0, \
        f"{script} failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    return proc.stdout


def test_train_mnist_gluon():
    out = _run("train_mnist.py", "--benchmark", "--epochs", "1",
               "--batch-size", "64")
    assert "epoch" in out.lower() or "accuracy" in out.lower()


def test_train_mnist_module():
    _run("train_mnist.py", "--benchmark", "--module", "--epochs", "1",
         "--batch-size", "64")


def test_sparse_linear_classification():
    out = _run("sparse_linear_classification.py", "--epochs", "1",
               "--num-features", "2000", "--batch-size", "256")
    assert "final-accuracy" in out


def test_quantize_int8():
    out = _run("quantize_int8.py", "--epochs", "1")
    assert "agreement" in out


def test_dcgan():
    out = _run("dcgan.py", "--epochs", "1", "--steps-per-epoch", "4",
               "--batch-size", "16")
    assert "sample-std" in out


def test_model_parallel_lstm():
    out = _run("model_parallel_lstm.py", timeout=300)
    assert "model-parallel == replicated: OK" in out


def test_word_language_model():
    out = _run("word_language_model.py", "--epochs", "1",
               "--batch-size", "8", "--bptt", "4")
    assert out.strip()


def test_ctc_ocr():
    out = _run("ctc_ocr.py", "--smoke")
    assert "smoke ok" in out


def test_lstm_bucketing():
    """The sym.RNN mega-op + BucketingModule path ([U:example/rnn/
    bucketing/] analog): perplexity must fall and buckets share weights."""
    out = _run("lstm_bucketing.py", "--epochs", "2", timeout=420)
    assert "final-perplexity" in out


def test_onnx_roundtrip_example():
    out = _run("onnx_roundtrip.py", "--epochs", "1", "--n", "256")
    assert "ONNX_ROUNDTRIP_OK" in out


def test_lstm_bucketing_cell_api():
    out = _run("lstm_bucketing.py", "--epochs", "2", "--cell-api")
    assert "final-perplexity" in out
