"""Control-flow ops (parity: [U:tests/python/unittest/test_contrib_control_flow.py]).

foreach/while_loop/cond over lax.scan/while-masked-scan/cond, including
autograd through the tape (one recorded node per loop) and the
RNN-unrolled-via-foreach == fused-lax.scan-RNN equivalence the reference
suite checks."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd


class TestForeach:
    def test_cumsum_semantics(self):
        data = mx.nd.array(np.arange(12, dtype=np.float32).reshape(4, 3))
        init = mx.nd.zeros((3,))

        def body(x, s):
            new = s + x
            return new, new

        outs, final = mx.nd.contrib.foreach(body, data, init)
        ref = np.cumsum(np.arange(12, dtype=np.float32).reshape(4, 3), axis=0)
        np.testing.assert_allclose(outs.asnumpy(), ref)
        np.testing.assert_allclose(final.asnumpy(), ref[-1])

    def test_multi_state_multi_out(self):
        data = mx.nd.array(np.ones((3, 2), np.float32))

        def body(x, states):
            a, b = states
            return [x + a, x * b], [a + 1, b * 2]

        outs, finals = mx.nd.contrib.foreach(body, data, [mx.nd.zeros((2,)), mx.nd.ones((2,))])
        np.testing.assert_allclose(outs[0].asnumpy(), [[1, 1], [2, 2], [3, 3]])
        np.testing.assert_allclose(outs[1].asnumpy(), [[1, 1], [2, 2], [4, 4]])
        np.testing.assert_allclose(finals[0].asnumpy(), [3, 3])
        np.testing.assert_allclose(finals[1].asnumpy(), [8, 8])

    def test_gradient_through_tape(self):
        data = mx.nd.array(np.random.RandomState(0).rand(5, 4).astype(np.float32))
        init = mx.nd.array(np.random.RandomState(1).rand(4).astype(np.float32))
        data.attach_grad()
        init.attach_grad()

        def body(x, s):
            new = mx.nd.tanh(s * x)
            return new, new

        with autograd.record():
            outs, final = mx.nd.contrib.foreach(body, data, init)
            loss = (outs * outs).sum()
        loss.backward()

        # numeric reference via finite differences on the same computation
        def f(d, i):
            s = i.copy()
            tot = 0.0
            for t in range(d.shape[0]):
                s = np.tanh(s * d[t])
                tot += (s * s).sum()
            return tot

        d0 = data.asnumpy().astype(np.float64)
        i0 = init.asnumpy().astype(np.float64)
        eps = 1e-5
        num = np.zeros_like(d0)
        for t in range(d0.shape[0]):
            for j in range(d0.shape[1]):
                dp, dm = d0.copy(), d0.copy()
                dp[t, j] += eps
                dm[t, j] -= eps
                num[t, j] = (f(dp, i0) - f(dm, i0)) / (2 * eps)
        np.testing.assert_allclose(data.grad.asnumpy(), num, rtol=1e-3, atol=1e-4)

    def test_rnn_unrolled_matches_fused_scan(self):
        """The reference's key control-flow check: an RNN stepped via
        foreach equals the fused (lax.scan) RNN op."""
        from incubator_mxnet_tpu import gluon

        T, B, I, H = 6, 2, 3, 5
        mx.random.seed(0)
        cell = gluon.rnn.RNNCell(H, input_size=I)
        cell.initialize()
        x_tbc = mx.nd.random.normal(shape=(T, B, I))
        h0 = mx.nd.zeros((B, H))

        def body(x_t, h):
            out, new_states = cell(x_t, [h])
            return out, new_states[0]

        outs, h_last = mx.nd.contrib.foreach(body, x_tbc, h0)

        # fused path: unroll the same cell (shares parameters)
        ref_outs, ref_state = cell.unroll(T, x_tbc, layout="TNC", merge_outputs=True)
        np.testing.assert_allclose(outs.asnumpy(), ref_outs.asnumpy(), rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(h_last.asnumpy(), ref_state[0].asnumpy(), rtol=1e-5, atol=1e-5)


class TestWhileLoop:
    def test_exact_trip_count_and_padding(self):
        # sum integers until total >= 10, max_iterations=8
        def cond_fn(i, total):
            return total < 10

        def func(i, total):
            return i, [i + 1, total + i]

        outs, (i_f, total_f) = mx.nd.contrib.while_loop(
            cond_fn, func, [mx.nd.array([1.0]), mx.nd.array([0.0])], max_iterations=8)
        # steps: i=1..4 (0+1+2+3+4 = 10 at i=4); outputs rows beyond are zeros
        np.testing.assert_allclose(total_f.asnumpy(), [10.0])
        np.testing.assert_allclose(i_f.asnumpy(), [5.0])
        got = outs.asnumpy().ravel()
        np.testing.assert_allclose(got[:4], [1, 2, 3, 4])
        np.testing.assert_allclose(got[4:], 0.0)

    def test_gradient(self):
        x = mx.nd.array([2.0])
        x.attach_grad()

        def cond_fn(v, n):
            return n < 3

        def func(v, n):
            return v, [v * v, n + 1]

        with autograd.record():
            outs, (v_f, n_f) = mx.nd.contrib.while_loop(
                cond_fn, func, [x, mx.nd.array([0.0])], max_iterations=5)
            loss = v_f.sum()  # ((x^2)^2)^2 = x^8
        loss.backward()
        np.testing.assert_allclose(v_f.asnumpy(), [2.0 ** 8])
        np.testing.assert_allclose(x.grad.asnumpy(), [8 * 2.0 ** 7], rtol=1e-5)


class TestCond:
    def test_branches(self):
        a = mx.nd.array([1.0, 2.0])
        b = mx.nd.array([10.0, 20.0])
        out_t = mx.nd.contrib.cond(mx.nd.array([1.0]), lambda: a + b, lambda: a - b)
        out_f = mx.nd.contrib.cond(mx.nd.array([0.0]), lambda: a + b, lambda: a - b)
        np.testing.assert_allclose(out_t.asnumpy(), [11.0, 22.0])
        np.testing.assert_allclose(out_f.asnumpy(), [-9.0, -18.0])

    def test_gradient_under_functional_trace(self):
        """cond operands are closure-captured (no explicit array inputs), so
        eager-tape grads don't apply — but under a functional trace (the
        hybridize/SPMDTrainer path) jax hoists the captured tracers and
        gradients flow through the selected branch."""
        import jax
        import jax.numpy as jnp

        from incubator_mxnet_tpu.ndarray.ndarray import NDArray

        def f(a):
            x = NDArray(a)
            out = mx.nd.contrib.cond(x > 0, lambda: x * x, lambda: -x)
            return out._data.sum()

        g_pos = jax.grad(f)(jnp.asarray([3.0]))
        g_neg = jax.grad(f)(jnp.asarray([-3.0]))
        np.testing.assert_allclose(np.asarray(g_pos), [6.0])
        np.testing.assert_allclose(np.asarray(g_neg), [-1.0])


def test_while_loop_early_exit_no_outputs():
    """Eager no-output loops take the lax.while_loop fast path: the loop
    must stop at the TRUE trip count (observable through the final vars)
    and still respect the max_iterations cap."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.ops.control_flow import while_loop

    outs, (i_f, x_f) = while_loop(
        lambda i, x: (x > 1.0),
        lambda i, x: ([], (i + 1, x * 0.5)),
        (mx.nd.array([0.0]), mx.nd.array([1000.0])),
        max_iterations=1000)
    assert outs == []
    assert float(i_f.asnumpy()[0]) == 10  # 1000 / 2^10 < 1, not 1000 iters
    np.testing.assert_allclose(float(x_f.asnumpy()[0]), 1000.0 / 1024, rtol=1e-6)

    # cap respected when the condition never goes false
    _, (i_c, _) = while_loop(
        lambda i, x: (x > -1.0),
        lambda i, x: ([], (i + 1, x + 1.0)),
        (mx.nd.array([0.0]), mx.nd.array([0.0])),
        max_iterations=7)
    assert float(i_c.asnumpy()[0]) == 7

    # with outputs the masked path is used and padding stays zeros
    outs, fin = while_loop(
        lambda i, v: i < 2,
        lambda i, v: (v * 2, (i + 1, v + 1)),
        (mx.nd.array([0.0]), mx.nd.array([3.0])),
        max_iterations=4)
    o = outs.asnumpy()
    assert o.shape[0] == 4 and (o[2:] == 0).all()
