"""Symbol/Executor tests (parity idioms: tests/python/unittest/
test_symbol.py + test_executor.py in the reference — compose, infer_shape,
json round-trip, bind fwd/bwd against the imperative oracle)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import sym
import incubator_mxnet_tpu.ndarray as nd


def _mlp_sym():
    data = sym.Variable("data")
    label = sym.Variable("softmax_label")
    fc1 = sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = sym.FullyConnected(act, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(fc2, label=label, name="softmax")


class TestSymbolGraph:
    def test_list_arguments_order_and_autocreate(self):
        out = _mlp_sym()
        args = out.list_arguments()
        assert args == ["data", "fc1_weight", "fc1_bias", "fc2_weight",
                        "fc2_bias", "softmax_label"]
        assert out.list_outputs() == ["softmax_output"]

    def test_no_bias(self):
        data = sym.Variable("data")
        fc = sym.FullyConnected(data, num_hidden=8, no_bias=True, name="fc")
        assert fc.list_arguments() == ["data", "fc_weight"]

    def test_infer_shape(self):
        out = _mlp_sym()
        arg_shapes, out_shapes, aux_shapes = out.infer_shape(
            data=(32, 10), softmax_label=(32,))
        d = dict(zip(out.list_arguments(), arg_shapes))
        assert d["fc1_weight"] == (16, 10)
        assert d["fc1_bias"] == (16,)
        assert d["fc2_weight"] == (4, 16)
        assert out_shapes == [(32, 4)]
        assert aux_shapes == []

    def test_infer_shape_conv(self):
        data = sym.Variable("data")
        c = sym.Convolution(data, num_filter=8, kernel=(3, 3), pad=(1, 1), name="conv")
        b = sym.BatchNorm(c, name="bn")
        arg_shapes, out_shapes, aux_shapes = b.infer_shape(data=(2, 3, 8, 8))
        d = dict(zip(b.list_arguments(), arg_shapes))
        assert d["conv_weight"] == (8, 3, 3, 3)
        assert d["conv_bias"] == (8,)
        assert d["bn_gamma"] == (8,)
        assert out_shapes[0] == (2, 8, 8, 8)
        assert aux_shapes == [(8,), (8,)]
        assert b.list_auxiliary_states() == ["bn_moving_mean", "bn_moving_var"]

    def test_json_roundtrip(self):
        out = _mlp_sym()
        s2 = sym.load_json(out.tojson())
        assert s2.list_arguments() == out.list_arguments()
        assert s2.list_outputs() == out.list_outputs()
        a1, o1, _ = out.infer_shape(data=(4, 6), softmax_label=(4,))
        a2, o2, _ = s2.infer_shape(data=(4, 6), softmax_label=(4,))
        assert a1 == a2 and o1 == o2

    def test_arithmetic_sugar_and_eval(self):
        a = sym.Variable("a")
        b = sym.Variable("b")
        c = 2.0 * a + b / 4.0 - 1.0
        out = c.eval(a=mx.nd.ones((2, 2)), b=mx.nd.ones((2, 2)) * 4)
        np.testing.assert_allclose(out[0].asnumpy(), np.full((2, 2), 2.0))

    def test_group_and_getitem(self):
        a = sym.Variable("a")
        s1 = sym.relu(a, name="r")
        s2 = sym.tanh(a, name="t")
        g = sym.Group([s1, s2])
        assert len(g) == 2
        assert g[0].list_outputs() == ["r_output"]

    def test_get_internals(self):
        out = _mlp_sym()
        internals = out.get_internals()
        names = internals.list_outputs()
        assert "relu1_output" in names
        feat = internals["relu1_output"]
        _, out_shapes, _ = feat.infer_shape(data=(8, 10))
        assert out_shapes == [(8, 16)]

    def test_compose(self):
        data = sym.Variable("data")
        net1 = sym.FullyConnected(data, num_hidden=8, name="fca")
        data2 = sym.Variable("d2")
        pre = sym.tanh(data2, name="pre")
        composed = net1(data=pre)
        assert "d2" in composed.list_arguments()
        assert "data" not in composed.list_arguments()


class TestExecutor:
    def test_forward_matches_imperative(self):
        out = _mlp_sym()
        ex = out.simple_bind(mx.cpu(), data=(8, 10), softmax_label=(8,))
        rng = np.random.RandomState(0)
        for k, v in ex.arg_dict.items():
            if k.endswith("weight"):
                v._data = mx.nd.array(rng.randn(*v.shape) * 0.1)._data
        x = rng.randn(8, 10).astype(np.float32)
        y = rng.randint(0, 4, (8,)).astype(np.float32)
        outs = ex.forward(is_train=False, data=x, softmax_label=y)

        h = nd.FullyConnected(mx.nd.array(x), ex.arg_dict["fc1_weight"],
                              ex.arg_dict["fc1_bias"], num_hidden=16)
        h = nd.Activation(h, act_type="relu")
        o = nd.FullyConnected(h, ex.arg_dict["fc2_weight"],
                              ex.arg_dict["fc2_bias"], num_hidden=4)
        ref = nd.softmax(o)
        np.testing.assert_allclose(outs[0].asnumpy(), ref.asnumpy(),
                                   rtol=1e-5, atol=1e-6)

    def test_backward_matches_autograd(self):
        out = _mlp_sym()
        ex = out.simple_bind(mx.cpu(), data=(8, 10), softmax_label=(8,))
        rng = np.random.RandomState(3)
        for k, v in ex.arg_dict.items():
            if k.endswith("weight"):
                v._data = mx.nd.array(rng.randn(*v.shape) * 0.1)._data
        x = rng.randn(8, 10).astype(np.float32)
        y = rng.randint(0, 4, (8,)).astype(np.float32)
        ex.forward(is_train=True, data=x, softmax_label=y)
        ex.backward()

        params = {k: mx.nd.array(v.asnumpy()) for k, v in ex.arg_dict.items()
                  if k not in ("data", "softmax_label")}
        for p in params.values():
            p.attach_grad()
        with mx.autograd.record():
            h = nd.FullyConnected(mx.nd.array(x), params["fc1_weight"],
                                  params["fc1_bias"], num_hidden=16)
            h = nd.Activation(h, act_type="relu")
            o = nd.FullyConnected(h, params["fc2_weight"],
                                  params["fc2_bias"], num_hidden=4)
            loss = nd.SoftmaxOutput(o, mx.nd.array(y))
        loss.backward()
        for k in params:
            np.testing.assert_allclose(
                ex.grad_dict[k].asnumpy(), params[k].grad.asnumpy(),
                rtol=1e-4, atol=1e-6, err_msg=k)

    def test_grad_req_null_and_add(self):
        a = sym.Variable("a")
        out = sym.sum(a * a, name="s")
        ex = out.bind(mx.cpu(), args={"a": mx.nd.ones((3,))},
                      grad_req="add")
        ex.forward(is_train=True)
        ex.backward()
        ex.backward()
        np.testing.assert_allclose(ex.grad_dict["a"].asnumpy(), np.full((3,), 4.0))

    def test_batchnorm_aux_update(self):
        data = sym.Variable("data")
        net = sym.BatchNorm(sym.FullyConnected(data, num_hidden=6, name="fc"),
                            name="bn", momentum=0.5)
        ex = net.simple_bind(mx.cpu(), data=(16, 4))
        rng = np.random.RandomState(0)
        ex.arg_dict["fc_weight"]._data = mx.nd.array(rng.randn(6, 4))._data
        ex.arg_dict["bn_gamma"]._data = mx.nd.ones((6,))._data
        mm0 = ex.aux_dict["bn_moving_mean"].asnumpy().copy()
        ex.forward(is_train=True, data=rng.randn(16, 4).astype(np.float32))
        mm1 = ex.aux_dict["bn_moving_mean"].asnumpy()
        assert not np.allclose(mm0, mm1)
        ex.forward(is_train=False, data=rng.randn(16, 4).astype(np.float32))
        np.testing.assert_allclose(mm1, ex.aux_dict["bn_moving_mean"].asnumpy())


class TestReviewRegressions:
    def test_auto_label_creation(self):
        """sym.SoftmaxOutput(data) without an explicit label must create
        '<name>_label' (the idiom Module's default label_names relies on)."""
        data = sym.Variable("data")
        fc = sym.FullyConnected(data, num_hidden=4, name="fc")
        out = sym.SoftmaxOutput(fc, name="softmax")
        assert "softmax_label" in out.list_arguments()
        ex = out.simple_bind(mx.cpu(), data=(8, 10))
        assert ex.arg_dict["softmax_label"].shape == (8,)

    def test_label_shape_inferred_from_data(self):
        data = sym.Variable("data")
        fc = sym.FullyConnected(data, num_hidden=4, name="fc")
        out = sym.SoftmaxOutput(fc, label=sym.Variable("softmax_label"), name="softmax")
        arg_shapes, _, _ = out.infer_shape(data=(8, 10))
        d = dict(zip(out.list_arguments(), arg_shapes))
        assert d["softmax_label"] == (8,)

    def test_variadic_concat(self):
        a = sym.Variable("a")
        b = sym.Variable("b")
        c = sym.concat(a, b, dim=1)
        _, out_shapes, _ = c.infer_shape(a=(2, 3), b=(2, 5))
        assert out_shapes == [(2, 8)]
        out = c.eval(a=mx.nd.ones((2, 3)), b=mx.nd.zeros((2, 5)))
        assert out[0].shape == (2, 8)

    def test_forward_unknown_feed_raises(self):
        data = sym.Variable("data")
        out = sym.relu(data, name="r")
        ex = out.simple_bind(mx.cpu(), data=(2, 2))
        with pytest.raises(ValueError, match="not an argument"):
            ex.forward(is_train=False, dta=np.zeros((2, 2), np.float32))

    def test_static_attrs_not_phantom_args(self):
        """Required static attrs (shape/axis/reps/...) passed as non-Symbol
        kwargs must become attrs, not auto-created tensor variables
        (advisor finding: sym.reshape(data, shape=...) created
        'reshape0_shape' and KeyError'd at bind)."""
        data = sym.Variable("data")
        for s in (sym.reshape(data, shape=(4, 2)),
                  sym.expand_dims(data, axis=0),
                  sym.tile(data, reps=(2, 1)),
                  sym.broadcast_to(sym.reshape(data, shape=(1, 8)), shape=(3, 8)),
                  sym.slice_axis(data, axis=0, begin=0, end=2)):
            args = s.list_arguments()
            assert args == ["data"], f"phantom args in {args}"
        r = sym.reshape(data, shape=(4, 2))
        out = r.eval(data=mx.nd.arange(8))
        assert out[0].shape == (4, 2)

    def test_executor_dropout_backward_uses_forward_mask(self):
        """backward() must re-execute the graph with the SAME PRNG key as
        the last forward so dropout masks agree (advisor finding: a fresh
        key made gradients inconsistent with forward outputs)."""
        data = sym.Variable("data")
        out = sym.Dropout(data, p=0.5, name="drop")
        ex = out.simple_bind(mx.cpu(), grad_req="write", data=(64, 64))
        rng = np.random.RandomState(3)
        x = rng.rand(64, 64).astype(np.float32) + 1.0  # strictly positive
        ex.forward(is_train=True, data=x)
        y = ex.outputs[0].asnumpy()
        ex.backward(out_grads=mx.nd.ones((64, 64)))
        g = ex.grad_dict["data"].asnumpy()
        # d(dropout(x))/dx elementwise == y/x (mask/(1-p)); must match the
        # mask actually drawn in forward
        np.testing.assert_allclose(g, y / x, rtol=1e-5)


class TestNamingAndAttrs:
    """mx.name.Prefix / NameManager + mx.AttrScope (parity:
    [U:python/mxnet/name.py], [U:python/mxnet/attribute.py])."""

    def test_prefix_scopes_auto_names(self):
        data = sym.Variable("data")
        with mx.name.Prefix("stage1_"):
            fc = sym.FullyConnected(data, num_hidden=4)
        args = fc.list_arguments()
        assert fc.name.startswith("stage1_fullyconnected")
        assert any(a.startswith("stage1_") and a.endswith("_weight") for a in args)

    def test_name_manager_counts_per_scope(self):
        data = sym.Variable("data")
        with mx.name.NameManager():
            a = sym.Activation(data, act_type="relu")
            b = sym.Activation(data, act_type="relu")
        assert a.name == "activation0"
        assert b.name == "activation1"
        # fresh manager restarts the count
        with mx.name.NameManager():
            c = sym.Activation(data, act_type="relu")
        assert c.name == "activation0"

    def test_prefix_applies_to_explicit_names(self):
        data = sym.Variable("data")
        with mx.name.Prefix("p_"):
            fc = sym.FullyConnected(data, num_hidden=4, name="fc")
        assert fc.name == "p_fc"
        assert "p_fc_weight" in fc.list_arguments()

    def test_attr_scope_attaches_and_reads_back(self):
        data = sym.Variable("data")
        with mx.AttrScope(ctx_group="dev1"):
            fc = sym.FullyConnected(data, num_hidden=4, name="fc1")
        assert fc.attr("ctx_group") == "dev1"
        assert fc.attr_dict()["fc1"]["ctx_group"] == "dev1"
        # symbols created outside the scope carry nothing
        fc2 = sym.FullyConnected(data, num_hidden=4, name="fc2")
        assert fc2.attr("ctx_group") is None

    def test_attr_scope_nesting_and_explicit_override(self):
        with mx.AttrScope(ctx_group="a", lr_mult="2"):
            with mx.AttrScope(ctx_group="b"):
                v = sym.Variable("w", attr={"ctx_group": "explicit"})
                fc = sym.FullyConnected(v, num_hidden=4, name="fc")
        assert v.attr("ctx_group") == "explicit"   # explicit wins
        assert v.attr("lr_mult") == "2"            # outer scope inherited
        assert fc.attr("ctx_group") == "b"         # inner scope wins

    def test_attr_scope_rejects_non_string(self):
        with pytest.raises(ValueError):
            mx.AttrScope(lr_mult=2.0)

    def test_attrs_do_not_leak_into_op_kwargs(self):
        # executor must still run: scope attrs are metadata, not op kwargs
        data = sym.Variable("data")
        with mx.AttrScope(ctx_group="dev1"):
            out = sym.FullyConnected(data, num_hidden=3, name="fc")
        ex = out.simple_bind(data=(2, 5))
        y = ex.forward()[0]
        assert y.shape == (2, 3)

    def test_attrs_roundtrip_json(self):
        data = sym.Variable("data")
        with mx.AttrScope(ctx_group="dev7"):
            out = sym.FullyConnected(data, num_hidden=3, name="fc")
        loaded = mx.sym.load_json(out.tojson())
        assert loaded.attr("ctx_group") == "dev7"

    def test_attr_scope_reaches_autocreated_params(self):
        data = sym.Variable("data")
        with mx.AttrScope(lr_mult="0.1"):
            fc = sym.FullyConnected(data, num_hidden=4, name="fc")
        d = fc.attr_dict()
        assert d["fc_weight"]["lr_mult"] == "0.1"
        assert d["fc_bias"]["lr_mult"] == "0.1"
        assert "data" not in d  # created outside the scope

    def test_attr_dict_excludes_internal_typed_attrs(self):
        v = sym.Variable("w", shape=(2, 3), attr={"k": "v"})
        assert v.attr_dict() == {"w": {"k": "v"}}
        assert v.infer_shape()[0]  # __shape__ still drives inference
        loaded = mx.sym.load_json(v.tojson())
        assert loaded.attr_dict()["w"]["k"] == "v"
        assert "shape" not in loaded.attr_dict()["w"]

    def test_variable_attr_rejects_non_string(self):
        with pytest.raises(ValueError):
            sym.Variable("w", attr={"foo": 2})

    def test_review_regressions(self):
        # zeros/ones resolve their name exactly once under Prefix
        with mx.name.Prefix("p_"):
            z = sym.zeros((2, 2))
            zn = sym.zeros((2, 2), name="z")
        assert z.name == "p__zeros0"
        assert zn.name == "p_z"
        # reference-style pre-dunder attr keys are stored once, readable
        v = sym.Variable("w", attr={"__ctx_group__": "dev1"})
        assert v.attr("ctx_group") == "dev1"
        assert v.attr("__ctx_group__") == "dev1"
        assert v.attr_dict()["w"]["ctx_group"] == "dev1"
        # as-bound scope object sees inherited outer attrs
        with mx.AttrScope(a="1"):
            with mx.AttrScope(b="2") as inner:
                assert inner.get() == {"a": "1", "b": "2"}

    def test_reserved_attr_keys_rejected(self):
        with pytest.raises(ValueError):
            mx.AttrScope(shape="NCHW")
        with pytest.raises(ValueError):
            mx.AttrScope(__dtype__="x")
        with pytest.raises(ValueError):
            sym.Variable("w", attr={"init": "Xavier"})

    def test_internal_var_metadata_hidden_from_attr_api(self):
        v = sym.Variable("w", shape=(2, 3), dtype="float16", init="Xavier")
        assert v.attr("dtype") is None and v.attr("init") is None
        assert v.attr_dict() == {}

    def test_variable_kwarg_attrs(self):
        v = sym.Variable("w", __ctx_group__="dev1")
        assert v.attr("ctx_group") == "dev1"
        with pytest.raises(ValueError):
            sym.Variable("w", ctx_group="dev1")  # non-dunder kwarg
        v2 = sym.Variable("w2", stype="row_sparse")
        assert v2.attr("stype") == "row_sparse"

    def test_json_init_attr_roundtrips_verbatim(self):
        # __init__ may itself be JSON (Initializer.dumps format) — must
        # stay a string through save/load
        v = sym.Variable("w", init='["Xavier", {"magnitude": 2}]')
        loaded = mx.sym.load_json(v.tojson())
        got = loaded._outputs[0][0].attrs["__init__"]
        assert got == '["Xavier", {"magnitude": 2}]'

    def test_deconvolution_no_bias_reference_default(self):
        """Deconvolution defaults no_bias=true in the reference — the
        symbol front end must honor the OP's signature default and not
        auto-create a bias."""
        data = sym.Variable("data")
        d = sym.Deconvolution(data, kernel=(2, 2), num_filter=3, name="d")
        assert d.list_arguments() == ["data", "d_weight"]
        # explicit opt-in restores the bias
        d2 = sym.Deconvolution(data, kernel=(2, 2), num_filter=3,
                               no_bias=False, name="d2")
        assert d2.list_arguments() == ["data", "d2_weight", "d2_bias"]


class TestCheckSymbolicHelpers:
    """check_symbolic_forward/backward — the reference test-utils idiom
    (SURVEY §4) on this Symbol/Executor stack."""

    def test_forward_against_numpy(self):
        from incubator_mxnet_tpu.utils.test_utils import check_symbolic_forward

        sym.symbol._reset_naming()
        x = sym.Variable("x")
        w = sym.Variable("w")
        out = sym.FullyConnected(x, w, num_hidden=3, no_bias=True,
                                 flatten=False, name="fc")
        xv = np.random.RandomState(0).rand(4, 5).astype(np.float32)
        wv = np.random.RandomState(1).rand(3, 5).astype(np.float32)
        check_symbolic_forward(out, [xv, wv], [xv @ wv.T], rtol=1e-5,
                               atol=1e-6)

    def test_backward_against_closed_form(self):
        from incubator_mxnet_tpu.utils.test_utils import check_symbolic_backward

        sym.symbol._reset_naming()
        a = sym.Variable("a")
        b = sym.Variable("b")
        out = sym.broadcast_mul(a, b, name="m")
        av = np.random.RandomState(2).rand(3, 4).astype(np.float32)
        bv = np.random.RandomState(3).rand(3, 4).astype(np.float32)
        og = np.random.RandomState(4).rand(3, 4).astype(np.float32)
        # d(a*b)/da = b * og;  d/db = a * og
        check_symbolic_backward(out, [av, bv], [og],
                                {"a": og * bv, "b": og * av},
                                rtol=1e-5, atol=1e-6)

    def test_backward_skips_none_expected(self):
        from incubator_mxnet_tpu.utils.test_utils import check_symbolic_backward

        sym.symbol._reset_naming()
        a = sym.Variable("a")
        b = sym.Variable("b")
        out = sym.broadcast_add(a, b, name="s")
        av = np.ones((2, 2), np.float32)
        bv = np.ones((2, 2), np.float32)
        check_symbolic_backward(out, [av, bv], [np.ones((2, 2), np.float32)],
                                {"a": np.ones((2, 2), np.float32), "b": None},
                                rtol=1e-6, atol=1e-7)

    def test_backward_none_out_grads_means_ones(self):
        from incubator_mxnet_tpu.utils.test_utils import check_symbolic_backward

        sym.symbol._reset_naming()
        a = sym.Variable("a")
        out = sym._mul_scalar(a, scalar=3.0, name="m")
        av = np.random.RandomState(5).rand(2, 3).astype(np.float32)
        check_symbolic_backward(out, [av], None,
                                {"a": np.full((2, 3), 3.0, np.float32)},
                                rtol=1e-6, atol=1e-7)


class TestScalarAndComparisonSugar:
    """Scalar op family + Symbol comparison operators (reference: the
    auto-generated _internal._*_scalar ops and Symbol __gt__ family —
    comparisons are GRAPH ops returning 1.0/0.0 float masks)."""

    def test_nd_scalar_family(self):
        x = np.array([[1.5, -2.0, 3.0]], np.float32)
        np.testing.assert_allclose(
            mx.nd._mod_scalar(mx.nd.array(x), scalar=2.0).asnumpy(),
            np.mod(x, 2.0))
        np.testing.assert_allclose(
            mx.nd._maximum_scalar(mx.nd.array(x), scalar=0.0).asnumpy(),
            np.maximum(x, 0.0))
        np.testing.assert_allclose(
            mx.nd._greater_scalar(mx.nd.array(x), scalar=1.0).asnumpy(),
            (x > 1.0).astype(np.float32))
        g = mx.nd._greater_scalar(mx.nd.array(x), scalar=1.0)
        assert str(g.dtype) == "float32"  # float mask, not bool

    def test_symbol_comparison_builds_graph(self):
        sym.symbol._reset_naming()
        a = sym.Variable("a")
        mask = a > 1.0
        out = mask * a  # keep only entries > 1
        exe = out.simple_bind(a=(2, 3))
        av = np.array([[0.5, 1.5, 2.5], [-1.0, 1.0, 3.0]], np.float32)
        exe.arg_dict["a"][:] = av
        got = exe.forward(is_train=False)[0].asnumpy()
        np.testing.assert_allclose(got, np.where(av > 1.0, av, 0.0))

    def test_symbol_vs_symbol_comparison_and_mod(self):
        sym.symbol._reset_naming()
        a = sym.Variable("a")
        b = sym.Variable("b")
        eq = (a == b)
        m = a % 3.0
        g = sym.Group([eq, m])
        exe = g.simple_bind(a=(4,), b=(4,))
        av = np.array([1, 2, 3, 4], np.float32)
        bv = np.array([1, 0, 3, 0], np.float32)
        exe.arg_dict["a"][:] = av
        exe.arg_dict["b"][:] = bv
        outs = exe.forward(is_train=False)
        np.testing.assert_allclose(outs[0].asnumpy(), (av == bv).astype(np.float32))
        np.testing.assert_allclose(outs[1].asnumpy(), np.mod(av, 3.0))

    def test_symbol_identity_semantics_preserved(self):
        # dict/set membership must keep identity hashing despite __eq__
        a = sym.Variable("a")
        b = sym.Variable("b")
        d = {a: 1, b: 2}
        assert d[a] == 1 and d[b] == 2
        assert a in d and b in d

    def test_comparison_edge_protocol(self):
        a = sym.Variable("a")
        b = sym.Variable("b")
        assert (a == None) is False          # noqa: E711 — protocol fallback
        assert (a != "x") is True
        with pytest.raises(TypeError):
            bool(a == b)                     # graph nodes have no truth value
        with pytest.raises(TypeError):
            a in [b]                         # membership needs truthiness


def test_fluent_methods():
    """Op-backed fluent methods on Symbol (reference chained style)."""
    from incubator_mxnet_tpu.symbol.symbol import _reset_naming
    _reset_naming()
    x = sym.var("data")
    y = (x.reshape(shape=(0, -1)).sum(axis=1, keepdims=True)
         .sqrt().clip(a_min=0.0, a_max=5.0))
    exe = y.simple_bind(data=(2, 3, 4))
    exe.arg_dict["data"][:] = np.ones((2, 3, 4), np.float32)
    out = exe.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(out, np.full((2, 1), np.sqrt(12.0)), rtol=1e-6)

    parts = x.split(num_outputs=3, axis=2)
    assert len(parts) == 3
    z = x.astype("float16").transpose(axes=(1, 0, 2)).flatten()
    assert z.infer_shape(data=(2, 3, 4))[0]  # shapes flow through the chain

    # reference positional forms map onto the op's static params —
    # including the splat style (x.reshape(0, -1) == x.reshape((0, -1)))
    assert (x.reshape(0, -1).infer_shape(data=(2, 3, 4))[1]
            == x.reshape((0, -1)).infer_shape(data=(2, 3, 4))[1])
    assert x.transpose(1, 0, 2).infer_shape(data=(2, 3, 4))[1] == [(3, 2, 4)]
    z2 = x.reshape((0, -1)).transpose((1, 0)).slice_axis(0, 0, 2)
    assert z2.infer_shape(data=(2, 3, 4))[0]
    assert len(x.split(3, 1)) == 3
    with pytest.raises(TypeError):
        x.sqrt(1)          # too many positionals
    with pytest.raises(TypeError):
        x.sum(1, axis=1)   # duplicate via positional + kwarg

    # fluent binding never clobbers core Symbol API
    assert callable(sym.var("w").attr_dict)
    assert sym.var("w").attr("__dtype__") is None


def test_fluent_methods_all_bound():
    """Every name in _FLUENT_METHODS is bound and builds a working node
    (shape inference flows) on a standard input."""
    from incubator_mxnet_tpu.symbol.symbol import _FLUENT_METHODS, _reset_naming

    _reset_naming()
    x = sym.var("data")
    # per-op kwargs where the bare call needs them
    needs = {
        "reshape": {"shape": (0, -1)}, "reshape_like": None,  # 2-tensor
        "expand_dims": {"axis": 0}, "tile": {"reps": (2, 1)},
        "pad": {"pad_width": (0, 0, 1, 1)}, "repeat": {"repeats": 2},
        "flip": {"axis": 0}, "broadcast_to": {"shape": (4, 6)},
        "broadcast_like": None, "split": {"num_outputs": 2, "axis": 1},
        "slice": {"begin": (0,), "end": (2,)},
        "slice_axis": {"axis": 0, "begin": 0, "end": 2},
        "slice_like": None, "take": None, "pick": None,
        "one_hot": {"depth": 3}, "clip": {"a_min": 0.0, "a_max": 1.0},
        "diag": {},
    }
    for name in _FLUENT_METHODS:
        assert hasattr(x, name), f"{name} not bound"
        kw = needs.get(name, {})
        if kw is None:  # needs a second tensor operand
            out = getattr(x, name)(sym.var("aux0"))
        else:
            out = getattr(x, name)(**kw)
        first = out[0] if isinstance(out, (list, tuple)) or len(out) > 1 else out
        shapes = first.infer_shape_partial(data=(4, 6))[1]
        assert shapes is not None, f"{name}: no shape inference"
    assert x.astype("float16") is not None
