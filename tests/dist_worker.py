"""Worker body for the multi-process distributed tier — the port of the
reference's [U:tests/nightly/dist_sync_kvstore.py] assertions, run at
``process_count == N`` (2 in CI; any N via DMLC_NUM_WORKER) on the CPU
backend via ``tools/launch_local.py``.  All expected values are exact
functions of the worker count.

Every check asserts EXACT aggregated values (deterministic inputs), the
reference suite's discipline.  Invoked by tests/test_dist.py; exits
non-zero on any failure.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def main():
    import jax

    try:  # drop the tunneled-TPU backend registered by sitecustomize, if any
        from jax._src import xla_bridge as _xb

        _xb._backend_factories.pop("axon", None)
    except Exception:
        pass

    import incubator_mxnet_tpu as mx

    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    expected = int(os.environ.get("DMLC_NUM_WORKER", "2"))
    assert nw == expected, f"worker count mismatch: {nw} != {expected}"

    # --- exact aggregated push/pull (int and string keys) ---------------
    kv.init(3, mx.nd.ones((4, 5)))
    kv.push(3, mx.nd.ones((4, 5)) * (rank + 1))  # sum over ranks of (r+1)
    out = mx.nd.zeros((4, 5))
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(),
                               (nw * (nw + 1) / 2) * np.ones((4, 5)))

    kv.init("weight0", mx.nd.zeros((3,)))
    kv.push("weight0", mx.nd.array([float(rank), 1.0, -1.0]))
    out = mx.nd.zeros((3,))
    kv.pull("weight0", out=out)
    np.testing.assert_allclose(
        out.asnumpy(), np.array([nw * (nw - 1) / 2, float(nw), -float(nw)]))

    # list-of-values aggregation first, then cross-worker reduce
    kv.push(3, [mx.nd.ones((4, 5)), mx.nd.ones((4, 5))])  # each worker: 2
    out2 = mx.nd.zeros((4, 5))
    kv.pull(3, out=out2)
    np.testing.assert_allclose(out2.asnumpy(), 2.0 * nw * np.ones((4, 5)))

    # --- updater on the store (optimizer-on-kvstore parity) -------------
    kvu = mx.kv.create("dist_sync")
    kvu.init(11, mx.nd.ones((2, 2)))

    def updater(key, grad, weight):
        weight += -0.1 * grad

    kvu._set_updater(updater)
    kvu.push(11, mx.nd.ones((2, 2)))  # agg grad = nw
    out = mx.nd.zeros((2, 2))
    kvu.pull(11, out=out)
    np.testing.assert_allclose(out.asnumpy(),
                               (1.0 - 0.1 * nw) * np.ones((2, 2)))

    # --- 2-bit gradient compression: wire dtype + exact quantized values
    kvc = mx.kv.create("dist_sync")
    kvc.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kvc.init(7, mx.nd.zeros((8,)))
    g = np.array([0.6, -0.7, 0.1, 0.0, 1.2, -0.2, 0.49, -0.51], np.float32)
    kvc.push(7, mx.nd.array(g))
    out = mx.nd.zeros((8,))
    kvc.pull(7, out=out)
    codes = np.array([1, -1, 0, 0, 1, 0, 0, -1], np.float32)
    # every worker pushes the same g → summed codes = nw·codes, times t
    np.testing.assert_allclose(out.asnumpy(), codes * nw * 0.5)
    assert kvc._last_wire_dtype == "int8", kvc._last_wire_dtype

    # error feedback: residual carries the quantization error into the next
    # push (residual[4] = 1.2 - 0.5 = 0.7 > t → fires on a zero gradient)
    kvc.push(7, mx.nd.zeros((8,)))
    kvc.pull(7, out=out)
    expect = np.zeros(8, np.float32)
    expect[4] = nw * 0.5
    np.testing.assert_allclose(out.asnumpy(), expect)

    # pushpull must take the same compressed wire path as push
    kvp = mx.kv.create("dist_sync")
    kvp.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kvp.init(9, mx.nd.zeros((4,)))
    outp = mx.nd.zeros((4,))
    kvp.pushpull(9, mx.nd.array([0.6, -0.7, 0.1, 0.0]), out=outp)
    np.testing.assert_allclose(outp.asnumpy(),
                               np.array([1, -1, 0, 0]) * nw * 0.5)
    assert kvp._last_wire_dtype == "int8", kvp._last_wire_dtype

    # --- barrier + SPMDTrainer.shard_batch over the N-process mesh ------
    kv.barrier()
    from incubator_mxnet_tpu.parallel import make_mesh, SPMDTrainer
    from incubator_mxnet_tpu import gluon

    mx.random.seed(0)
    net = gluon.nn.Dense(4, flatten=False)
    net.initialize()
    net(mx.nd.zeros((2, 8)))  # materialize shapes

    def loss_fn(out, label):
        return ((out - label) ** 2).mean(axis=-1)

    mesh = make_mesh()  # pure dp over one device per process
    assert mesh.devices.size == nw
    trainer = SPMDTrainer(net, loss_fn, "sgd", {"learning_rate": 0.1}, mesh=mesh)
    # each process feeds its LOCAL 1/nw shard of the global batch
    rng = np.random.RandomState(42 + rank)
    x = mx.nd.array(rng.rand(4, 8).astype(np.float32))
    y = mx.nd.array(rng.rand(4, 4).astype(np.float32))
    l0 = float(trainer.step(x, y).asscalar())
    for _ in range(20):
        loss = trainer.step(x, y)
    l1 = float(loss.asscalar())
    assert np.isfinite(l0) and l1 < l0, (l0, l1)

    print(f"dist_worker rank {rank}/{nw}: all assertions passed", flush=True)


if __name__ == "__main__":
    main()
