"""Predictor (c_predict parity) + mx.config env surface."""
import numpy as np

import incubator_mxnet_tpu as mx
import incubator_mxnet_tpu.symbol as S
from incubator_mxnet_tpu.predictor import (Predictor, _split_param_key,
                                           load_checkpoint)


class TestPredictor:
    def _checkpoint(self, tmp_path):
        S.symbol._reset_naming()
        data = S.var("data")
        fc = S.FullyConnected(data, num_hidden=4, name="fc1")
        sym = S.Activation(fc, act_type="tanh", name="t1")
        rng = np.random.RandomState(0)
        shapes, _, _ = sym.infer_shape(data=(2, 3))
        params = {}
        for name, shp in zip(sym.list_arguments(), shapes):
            if name != "data":
                params["arg:" + name] = mx.nd.array(rng.randn(*shp).astype(np.float32))
        sym_path = str(tmp_path / "m-symbol.json")
        with open(sym_path, "w") as f:
            f.write(sym.tojson())
        par_path = str(tmp_path / "m-0000.params")
        mx.nd.save(par_path, params)
        return sym, params, sym_path, par_path

    def test_predict_matches_bind(self, tmp_path):
        sym, params, sym_path, par_path = self._checkpoint(tmp_path)
        pred = Predictor(sym_path, par_path, {"data": (2, 3)})
        x = np.random.RandomState(1).rand(2, 3).astype(np.float32)
        out = pred.predict(data=x)

        exe = sym.simple_bind(data=(2, 3))
        exe.arg_dict["data"][:] = x
        for k, v in params.items():
            exe.arg_dict[k.split(":", 1)[1]][:] = v.asnumpy()
        ref = exe.forward(is_train=False)[0].asnumpy()
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    def test_set_input_forward_get_output(self, tmp_path):
        _, _, sym_path, par_path = self._checkpoint(tmp_path)
        pred = Predictor(sym_path, par_path, {"data": (2, 3)})
        pred.set_input("data", np.ones((2, 3), np.float32))
        pred.forward()
        assert pred.get_output(0).shape == (2, 4)


class TestParamKeySplit:
    """Satellite (ISSUE 8): only the literal ``arg:``/``aux:`` prefixes
    are stripped — other colons belong to the parameter's name, and
    prefixed / unprefixed checkpoints load identically."""

    def test_split_rules(self):
        assert _split_param_key("arg:weight") == ("arg", "weight")
        assert _split_param_key("aux:moving_mean") == ("aux", "moving_mean")
        assert _split_param_key("weight") == (None, "weight")
        # a colon that is NOT an arg:/aux: prefix stays in the name
        # (the old split(":", 1) mangled this into "weight")
        assert _split_param_key("encoder:weight") == (None, "encoder:weight")
        assert _split_param_key("arg:scope:weight") == ("arg", "scope:weight")

    def _bn_model(self):
        S.symbol._reset_naming()
        data = S.var("data")
        fc = S.FullyConnected(data, num_hidden=4, name="fc1")
        sym = S.BatchNorm(fc, name="bn1")
        rng = np.random.RandomState(0)
        shapes, _, aux_shapes = sym.infer_shape(data=(2, 3))
        args, auxs = {}, {}
        for name, shp in zip(sym.list_arguments(), shapes):
            if name != "data":
                args[name] = rng.randn(*shp).astype(np.float32)
        for name, shp in zip(sym.list_auxiliary_states(), aux_shapes):
            auxs[name] = rng.rand(*shp).astype(np.float32)
        return sym, args, auxs

    def test_prefixed_and_bare_load_identically(self):
        sym, args, auxs = self._bn_model()
        x = np.random.RandomState(1).rand(2, 3).astype(np.float32)

        prefixed = {f"arg:{k}": mx.nd.array(v) for k, v in args.items()}
        prefixed.update({f"aux:{k}": mx.nd.array(v) for k, v in auxs.items()})
        bare = {k: mx.nd.array(v) for k, v in args.items()}
        bare.update({k: mx.nd.array(v) for k, v in auxs.items()})

        out_p = Predictor(sym, prefixed, {"data": (2, 3)}).predict(data=x)
        out_b = Predictor(sym, bare, {"data": (2, 3)}).predict(data=x)
        np.testing.assert_array_equal(out_p, out_b)

    def test_load_checkpoint_classifies_aux(self):
        sym, args, auxs = self._bn_model()
        bare = {k: mx.nd.array(v) for k, v in args.items()}
        bare.update({k: mx.nd.array(v) for k, v in auxs.items()})
        _, arg_d, aux_d = load_checkpoint(sym, bare)
        assert set(aux_d) == set(auxs)
        assert set(arg_d) == set(args)


class TestSharedParamRebind:
    """Satellite (ISSUE 8): rebinding for a new input shape shares the
    parameter arrays — one device copy total — and ``reshape`` reuses a
    previously bound executor outright."""

    def _pred(self, tmp_path_or_none=None):
        S.symbol._reset_naming()
        data = S.var("data")
        fc = S.FullyConnected(data, num_hidden=4, name="fc1")
        sym = S.Activation(fc, act_type="tanh", name="t1")
        rng = np.random.RandomState(0)
        params = {"arg:fc1_weight": mx.nd.array(
                      rng.randn(4, 3).astype(np.float32)),
                  "arg:fc1_bias": mx.nd.array(
                      rng.randn(4).astype(np.float32))}
        return sym, params, Predictor(sym, params, {"data": (2, 3)})

    def test_reshape_shares_param_objects(self):
        _, _, pred = self._pred()
        exe1 = pred._exe
        w1 = exe1.arg_dict["fc1_weight"]
        pred.reshape({"data": (8, 3)})
        exe2 = pred._exe
        assert exe2 is not exe1
        # the SAME NDArray objects back both executors: no re-copy
        assert exe2.arg_dict["fc1_weight"] is w1
        assert exe2.arg_dict["fc1_bias"] is exe1.arg_dict["fc1_bias"]

    def test_reshape_reuses_cached_executor(self):
        _, _, pred = self._pred()
        exe1 = pred._exe
        pred.forward()
        assert pred.is_warm()
        pred.reshape({"data": (8, 3)})
        pred.reshape({"data": (2, 3)})
        assert pred._exe is exe1           # signature seen before: cache hit
        assert pred.is_warm()              # jit cache rode along
        assert pred.compile_stats()["executors"] == 2

    def test_reshape_results_correct(self):
        sym, params, pred = self._pred()
        rng = np.random.RandomState(2)
        x = rng.rand(8, 3).astype(np.float32)
        out = pred.reshape({"data": (8, 3)}).predict(data=x)

        exe = sym.simple_bind(data=(8, 3))
        exe.arg_dict["data"][:] = x
        for k, v in params.items():
            exe.arg_dict[k.split(":", 1)[1]][:] = v.asnumpy()
        ref = exe.forward(is_train=False)[0].asnumpy()
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    def test_reshape_unknown_input_raises(self):
        _, _, pred = self._pred()
        import pytest

        with pytest.raises(KeyError):
            pred.reshape({"nope": (2, 3)})


class TestConfig:
    def test_describe_lists_vars(self):
        s = mx.config.describe()
        assert "MXNET_ENGINE_TYPE" in s and "MXNET_TPU_FLASH" in s

    def test_memory_info_shape(self):
        info = mx.config.memory_info()
        assert isinstance(info, dict) and len(info) >= 1
        first = next(iter(info.values()))
        assert set(first) == {"bytes_in_use", "peak_bytes_in_use", "bytes_limit"}
