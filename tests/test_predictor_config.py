"""Predictor (c_predict parity) + mx.config env surface."""
import numpy as np

import incubator_mxnet_tpu as mx
import incubator_mxnet_tpu.symbol as S
from incubator_mxnet_tpu.predictor import Predictor


class TestPredictor:
    def _checkpoint(self, tmp_path):
        S.symbol._reset_naming()
        data = S.var("data")
        fc = S.FullyConnected(data, num_hidden=4, name="fc1")
        sym = S.Activation(fc, act_type="tanh", name="t1")
        rng = np.random.RandomState(0)
        shapes, _, _ = sym.infer_shape(data=(2, 3))
        params = {}
        for name, shp in zip(sym.list_arguments(), shapes):
            if name != "data":
                params["arg:" + name] = mx.nd.array(rng.randn(*shp).astype(np.float32))
        sym_path = str(tmp_path / "m-symbol.json")
        with open(sym_path, "w") as f:
            f.write(sym.tojson())
        par_path = str(tmp_path / "m-0000.params")
        mx.nd.save(par_path, params)
        return sym, params, sym_path, par_path

    def test_predict_matches_bind(self, tmp_path):
        sym, params, sym_path, par_path = self._checkpoint(tmp_path)
        pred = Predictor(sym_path, par_path, {"data": (2, 3)})
        x = np.random.RandomState(1).rand(2, 3).astype(np.float32)
        out = pred.predict(data=x)

        exe = sym.simple_bind(data=(2, 3))
        exe.arg_dict["data"][:] = x
        for k, v in params.items():
            exe.arg_dict[k.split(":", 1)[1]][:] = v.asnumpy()
        ref = exe.forward(is_train=False)[0].asnumpy()
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    def test_set_input_forward_get_output(self, tmp_path):
        _, _, sym_path, par_path = self._checkpoint(tmp_path)
        pred = Predictor(sym_path, par_path, {"data": (2, 3)})
        pred.set_input("data", np.ones((2, 3), np.float32))
        pred.forward()
        assert pred.get_output(0).shape == (2, 4)


class TestConfig:
    def test_describe_lists_vars(self):
        s = mx.config.describe()
        assert "MXNET_ENGINE_TYPE" in s and "MXNET_TPU_FLASH" in s

    def test_memory_info_shape(self):
        info = mx.config.memory_info()
        assert isinstance(info, dict) and len(info) >= 1
        first = next(iter(info.values()))
        assert set(first) == {"bytes_in_use", "peak_bytes_in_use", "bytes_limit"}
