"""mx.rtc — runtime-compiled Pallas kernels (parity idiom:
tests/python/gpu/test_operator_gpu.py::test_cuda_rtc in the reference:
compile source at runtime, launch, check values)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx


SRC = '''
def scale_add(x_ref, y_ref, o_ref):
    o_ref[...] = 2.0 * x_ref[...] + y_ref[...]

def saxpy_block(x_ref, y_ref, o_ref):
    # blocked variant: each grid step sees one (8, 128) tile
    o_ref[...] = 0.5 * x_ref[...] + y_ref[...]
'''


def test_string_source_compile_and_launch():
    mod = mx.rtc.PallasModule(SRC, exports=["scale_add", "saxpy_block"])
    x = mx.nd.array(np.random.rand(16, 128).astype(np.float32))
    y = mx.nd.array(np.random.rand(16, 128).astype(np.float32))
    k = mod.get_kernel("scale_add", out_shapes=[(16, 128)])
    z = k.launch([x, y])
    np.testing.assert_allclose(z.asnumpy(), 2 * x.asnumpy() + y.asnumpy(),
                               rtol=1e-6)


def test_grid_launch_with_block_specs():
    from jax.experimental import pallas as pl

    mod = mx.rtc.PallasModule(SRC)
    n_blocks = 4
    spec = pl.BlockSpec((8, 128), lambda i: (i, 0))
    k = mod.get_kernel("saxpy_block", out_shapes=[(8 * n_blocks, 128)],
                       grid=(n_blocks,), in_specs=[spec, spec],
                       out_specs=[spec])
    x = mx.nd.array(np.random.rand(8 * n_blocks, 128).astype(np.float32))
    y = mx.nd.array(np.random.rand(8 * n_blocks, 128).astype(np.float32))
    z = k.launch([x, y])
    np.testing.assert_allclose(z.asnumpy(), 0.5 * x.asnumpy() + y.asnumpy(),
                               rtol=1e-6)


def test_callable_source_and_multiple_outputs():
    def minmax(x_ref, lo_ref, hi_ref):
        lo_ref[...] = x_ref[...].min(keepdims=True)
        hi_ref[...] = x_ref[...].max(keepdims=True)

    mod = mx.rtc.PallasModule(minmax)
    k = mod.get_kernel("minmax", out_shapes=[(1, 1), (1, 1)])
    x = mx.nd.array(np.random.rand(32, 32).astype(np.float32))
    lo, hi = k.launch([x])
    np.testing.assert_allclose(lo.asnumpy().ravel(), [x.asnumpy().min()],
                               rtol=1e-6)
    np.testing.assert_allclose(hi.asnumpy().ravel(), [x.asnumpy().max()],
                               rtol=1e-6)


def test_unknown_kernel_and_missing_export():
    mod = mx.rtc.PallasModule(SRC)
    with pytest.raises(ValueError):
        mod.get_kernel("nope", out_shapes=[(2, 2)])
    with pytest.raises(ValueError):
        mx.rtc.PallasModule(SRC, exports=["not_there"])


def test_indented_source_dedents():
    src = '''
        def twice(x_ref, o_ref):
            o_ref[...] = 2.0 * x_ref[...]
    '''
    mod = mx.rtc.PallasModule(src)
    x = mx.nd.array(np.random.rand(4, 8).astype(np.float32))
    z = mod.get_kernel("twice", out_shapes=[(4, 8)]).launch([x])
    np.testing.assert_allclose(z.asnumpy(), 2 * x.asnumpy(), rtol=1e-6)


def test_bare_out_spec_and_dtype_validation():
    from jax.experimental import pallas as pl

    mod = mx.rtc.PallasModule(SRC)
    spec = pl.BlockSpec((8, 128), lambda i: (i, 0))
    k = mod.get_kernel("saxpy_block", out_shapes=[(16, 128)], grid=(2,),
                       in_specs=[spec, spec], out_specs=spec)  # bare spec
    x = mx.nd.array(np.random.rand(16, 128).astype(np.float32))
    y = mx.nd.array(np.random.rand(16, 128).astype(np.float32))
    np.testing.assert_allclose(k.launch([x, y]).asnumpy(),
                               0.5 * x.asnumpy() + y.asnumpy(), rtol=1e-6)
    with pytest.raises(ValueError):
        mod.get_kernel("scale_add", out_shapes=[(2, 2), (2, 2)],
                       out_dtypes=["float32"])


def test_launch_reuses_compiled_call():
    mod = mx.rtc.PallasModule(SRC)
    k = mod.get_kernel("scale_add", out_shapes=[(8, 8)])
    x = mx.nd.array(np.ones((8, 8), np.float32))
    k.launch([x, x])
    k.launch([x, x])
    assert len(k._calls) == 1  # second launch hit the cache


def test_out_specs_count_validated_at_get_kernel():
    from jax.experimental import pallas as pl

    mod = mx.rtc.PallasModule(SRC)
    spec = pl.BlockSpec((8, 128), lambda i: (i, 0))
    with pytest.raises(ValueError):
        mod.get_kernel("scale_add", out_shapes=[(2, 2), (2, 2)],
                       out_dtypes=["float32", "float32"],
                       grid=(1,), out_specs=spec)
