"""RecordIO + image pipeline tests (parity idioms: test_recordio.py /
test_io.py / test_image.py in the reference)."""
import os
import subprocess
import sys

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import recordio


@pytest.fixture(scope="module")
def img_pack(tmp_path_factory):
    """12 synthetic JPEGs in 2 class dirs, packed via tools/im2rec.py."""
    root = tmp_path_factory.mktemp("imgs")
    from PIL import Image
    rng = np.random.RandomState(0)
    for i in range(12):
        cls = root / ("cat" if i % 2 == 0 else "dog")
        cls.mkdir(exist_ok=True)
        h, w = rng.randint(40, 120), rng.randint(40, 120)
        arr = rng.randint(0, 255, (h, w, 3), dtype=np.uint8)
        Image.fromarray(arr).save(str(cls / f"img{i}.jpg"), quality=90)
    prefix = str(tmp_path_factory.mktemp("pack") / "data")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    subprocess.run([sys.executable, os.path.join(repo, "tools", "im2rec.py"),
                    prefix, str(root)], check=True, capture_output=True)
    return prefix


class TestRecordIO:
    def test_write_read_roundtrip(self, tmp_path):
        path = str(tmp_path / "t.rec")
        w = recordio.MXRecordIO(path, "w")
        payloads = [b"hello", b"x" * 1001, b""]
        for p in payloads:
            w.write(p)
        w.close()
        r = recordio.MXRecordIO(path, "r")
        got = [r.read() for _ in payloads]
        assert got == payloads
        assert r.read() is None

    def test_indexed_roundtrip(self, tmp_path):
        rec = str(tmp_path / "t.rec")
        idx = str(tmp_path / "t.idx")
        w = recordio.MXIndexedRecordIO(idx, rec, "w")
        for i in range(5):
            w.write_idx(i * 7, f"rec{i}".encode())
        w.close()
        r = recordio.MXIndexedRecordIO(idx, rec, "r")
        assert r.read_idx(21) == b"rec3"
        assert r.read_idx(0) == b"rec0"
        assert r.keys == [0, 7, 14, 21, 28]

    def test_pack_unpack_scalar_and_vector_label(self):
        h = recordio.IRHeader(0, 3.0, 42, 0)
        s = recordio.pack(h, b"payload")
        h2, data = recordio.unpack(s)
        assert data == b"payload" and h2.label == 3.0 and h2.id == 42

        hv = recordio.IRHeader(0, [1.0, 2.0, 3.0], 7, 0)
        s = recordio.pack(hv, b"img")
        h3, data = recordio.unpack(s)
        np.testing.assert_allclose(h3.label, [1.0, 2.0, 3.0])
        assert data == b"img"

    def test_pack_img_roundtrip(self):
        # smooth gradient: JPEG-friendly, so the roundtrip error is tight
        y, x = np.mgrid[0:16, 0:16]
        arr = np.stack([y * 8, x * 8, (y + x) * 4], axis=-1).astype(np.uint8)
        s = recordio.pack_img(recordio.IRHeader(0, 1.0, 0, 0), arr, quality=100)
        h, img = recordio.unpack_img(s)
        assert img.shape == (16, 16, 3)
        assert np.abs(img.astype(int) - arr.astype(int)).mean() < 3


class TestImageRecordIter:
    def test_native_pipeline(self, img_pack):
        it = mx.io.ImageRecordIter(img_pack + ".rec", (3, 32, 32),
                                   batch_size=5, shuffle=True, seed=3,
                                   rand_mirror=True)
        assert it.num_samples == 12
        batches = list(it)
        assert len(batches) == 3
        assert batches[0].data[0].shape == (5, 3, 32, 32)
        assert batches[-1].pad == 3
        it.reset()
        assert len(list(it)) == 3

    def test_label_multiset_matches_fallback(self, img_pack):
        import incubator_mxnet_tpu.io.record_iter as ri

        def labels(it):
            out = []
            for b in it:
                valid = b.data[0].shape[0] - (b.pad or 0)
                out.extend(float(x) for x in b.label[0].asnumpy()[:valid])
            return sorted(out)

        it_native = mx.io.ImageRecordIter(img_pack + ".rec", (3, 32, 32), batch_size=5)
        assert it_native._handle is not None, "native lib should be available"
        saved, ri._LIB = ri._LIB, None
        try:
            it_py = mx.io.ImageRecordIter(img_pack + ".rec", (3, 32, 32), batch_size=5)
            assert it_py._handle is None
            assert labels(it_native) == labels(it_py) == [0.0] * 6 + [1.0] * 6
        finally:
            ri._LIB = saved

    def test_sharding_partitions(self, img_pack):
        its = [mx.io.ImageRecordIter(img_pack + ".rec", (3, 32, 32),
                                     batch_size=4, part_index=i, num_parts=3)
               for i in range(3)]
        counts = [it.num_samples for it in its]
        assert sum(counts) == 12 and all(c == 4 for c in counts)

    def test_normalization_applied(self, img_pack):
        it = mx.io.ImageRecordIter(img_pack + ".rec", (3, 32, 32), batch_size=12,
                                   mean_r=128, mean_g=128, mean_b=128,
                                   std_r=64, std_g=64, std_b=64)
        b = next(iter(it))
        arr = b.data[0].asnumpy()
        assert arr.min() >= -2.0 and arr.max() <= 2.0
        assert abs(arr.mean()) < 0.6  # roughly centered


class TestImageModule:
    def test_imdecode_imresize_crop(self):
        from PIL import Image
        import io as pio
        arr = np.random.RandomState(1).randint(0, 255, (40, 60, 3), np.uint8)
        buf = pio.BytesIO()
        Image.fromarray(arr).save(buf, format="PNG")
        img = mx.image.imdecode(buf.getvalue())
        assert img.shape == (40, 60, 3)
        np.testing.assert_array_equal(img.asnumpy(), arr)  # png lossless

        r = mx.image.imresize(img, 30, 20)
        assert r.shape == (20, 30, 3)
        c, _ = mx.image.center_crop(img, (32, 32))
        assert c.shape == (32, 32, 3)
        rs = mx.image.resize_short(img, 32)
        assert min(rs.shape[:2]) == 32

    def test_color_normalize(self):
        img = mx.nd.ones((4, 4, 3)) * 100
        out = mx.image.color_normalize(img, mx.nd.array(np.array([50., 50., 50.], np.float32)),
                                       mx.nd.array(np.array([25., 25., 25.], np.float32)))
        np.testing.assert_allclose(out.asnumpy(), np.full((4, 4, 3), 2.0))

    def test_image_iter_from_rec(self, img_pack):
        it = mx.image.ImageIter(4, (3, 28, 28), path_imgrec=img_pack + ".rec",
                                rand_crop=True, rand_mirror=True)
        b = next(it)
        assert b.data[0].shape == (4, 3, 28, 28)
        assert b.label[0].shape == (4,)

    def test_create_augmenter_pipeline(self):
        augs = mx.image.CreateAugmenter((3, 24, 24), resize=28, rand_crop=True,
                                        rand_mirror=True, mean=True, std=True)
        img = mx.nd.array(np.random.RandomState(0).randint(
            0, 255, (40, 50, 3)).astype(np.uint8), dtype="uint8")
        for aug in augs:
            img = aug(img)
        assert img.shape == (24, 24, 3)
        assert img.dtype == np.float32


class TestReviewRegressions:
    def test_vector_label_native_matches_fallback(self, tmp_path):
        """flag>0 records: native must read label[0] like the fallback."""
        import incubator_mxnet_tpu.io.record_iter as ri
        from PIL import Image
        import io as pio
        rec_path = str(tmp_path / "v.rec")
        w = recordio.MXRecordIO(rec_path, "w")
        rng = np.random.RandomState(0)
        for i in range(4):
            buf = pio.BytesIO()
            Image.fromarray(rng.randint(0, 255, (20, 20, 3), np.uint8)).save(buf, "JPEG")
            w.write(recordio.pack(recordio.IRHeader(0, [float(i + 1), 9.0], i, 0),
                                  buf.getvalue()))
        w.close()

        def labels(it):
            return [float(x) for b in it
                    for x in b.label[0].asnumpy()[:b.data[0].shape[0] - (b.pad or 0)]]

        it_native = mx.io.ImageRecordIter(rec_path, (3, 16, 16), batch_size=4)
        assert it_native._handle is not None
        saved, ri._LIB = ri._LIB, None
        try:
            it_py = mx.io.ImageRecordIter(rec_path, (3, 16, 16), batch_size=4)
        finally:
            ri._LIB = saved
        assert labels(it_native) == labels(it_py) == [1.0, 2.0, 3.0, 4.0]

    def test_png_sources_reencoded_by_im2rec(self, tmp_path):
        """PNG inputs must not become silent zero tensors in the native path."""
        from PIL import Image
        root = tmp_path / "pngs"
        root.mkdir()
        arr = np.full((30, 30, 3), 200, np.uint8)
        for i in range(3):
            Image.fromarray(arr).save(str(root / f"p{i}.png"))
        prefix = str(tmp_path / "pk")
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        subprocess.run([sys.executable, os.path.join(repo, "tools", "im2rec.py"),
                        prefix, str(root)], check=True, capture_output=True)
        it = mx.io.ImageRecordIter(prefix + ".rec", (3, 16, 16), batch_size=3)
        assert it._handle is not None
        b = next(iter(it))
        # pixels ≈ 200, nothing zeroed out
        assert b.data[0].asnumpy().mean() > 150

    def test_grayscale_uses_fallback(self, img_pack):
        it = mx.io.ImageRecordIter(img_pack + ".rec", (1, 24, 24), batch_size=4)
        assert it._handle is None  # native path is RGB-only
        b = next(iter(it))
        assert b.data[0].shape == (4, 1, 24, 24)

    def test_indexed_writer_reset(self, tmp_path):
        w = recordio.MXIndexedRecordIO(str(tmp_path / "a.idx"),
                                       str(tmp_path / "a.rec"), "w")
        w.write_idx(0, b"old")
        w.reset()
        w.write_idx(1, b"new")
        w.close()
        r = recordio.MXIndexedRecordIO(str(tmp_path / "a.idx"),
                                       str(tmp_path / "a.rec"), "r")
        assert r.keys == [1] and r.read_idx(1) == b"new"

    def test_iter_next_protocol(self, img_pack):
        it = mx.io.ImageRecordIter(img_pack + ".rec", (3, 16, 16), batch_size=4)
        seen = 0
        while it.iter_next():
            d = it.getdata()
            assert d[0].shape == (4, 3, 16, 16)
            batch = it.next()  # must consume the same batch, not skip one
            seen += batch.data[0].shape[0] - (batch.pad or 0)
        assert seen == 12

    def test_augmentation_varies_across_epochs(self, img_pack):
        it = mx.io.ImageRecordIter(img_pack + ".rec", (3, 24, 24), batch_size=12,
                                   rand_crop=True, rand_mirror=True, seed=7)
        assert it._handle is not None
        e1 = next(iter(it)).data[0].asnumpy().copy()
        it.reset()
        e2 = next(iter(it)).data[0].asnumpy()
        assert not np.allclose(e1, e2), "augment RNG must advance across epochs"


# ---------------------------------------------------------------------------
# classification augmenter family (round 5 — the color/PCA/gray/sized-crop
# augs of [U:python/mxnet/image/image.py])
# ---------------------------------------------------------------------------


class TestClassificationAugmenters:
    def _img(self, h=32, w=48, seed=0):
        rng = np.random.RandomState(seed)
        return mx.nd.array(rng.randint(0, 255, (h, w, 3)).astype(np.float32))

    def test_brightness_contrast_saturation_formulas(self):
        import random
        from incubator_mxnet_tpu import image as img_mod

        src = self._img()
        arr = src.asnumpy()
        coef = np.array([0.299, 0.587, 0.114], np.float32)

        random.seed(3)
        out = img_mod.BrightnessJitterAug(0.5)(src).asnumpy()
        random.seed(3)
        alpha = 1.0 + random.uniform(-0.5, 0.5)
        np.testing.assert_allclose(out, arr * alpha, rtol=1e-5)

        random.seed(4)
        out = img_mod.ContrastJitterAug(0.5)(src).asnumpy()
        random.seed(4)
        alpha = 1.0 + random.uniform(-0.5, 0.5)
        gray_mean = (arr * coef).sum(2).mean()
        np.testing.assert_allclose(out, arr * alpha + gray_mean * (1 - alpha),
                                   rtol=1e-4)

        random.seed(5)
        out = img_mod.SaturationJitterAug(0.5)(src).asnumpy()
        random.seed(5)
        alpha = 1.0 + random.uniform(-0.5, 0.5)
        gray = (arr * coef).sum(2, keepdims=True)
        np.testing.assert_allclose(out, arr * alpha + gray * (1 - alpha),
                                   rtol=1e-4)

    def test_hue_preserves_luma_and_identity_at_zero(self):
        from incubator_mxnet_tpu import image as img_mod

        src = self._img()
        out = img_mod.HueJitterAug(0.0)(src).asnumpy()
        np.testing.assert_allclose(out, src.asnumpy(), atol=1e-3)
        # the YIQ rotation leaves the Y (luma) channel invariant
        out = img_mod.HueJitterAug(0.4)(src).asnumpy()
        coef = np.array([0.299, 0.587, 0.114], np.float32)
        np.testing.assert_allclose((out * coef).sum(2), (src.asnumpy() * coef).sum(2),
                                   rtol=1e-3, atol=1e-2)

    def test_lighting_gray_and_sized_crop(self):
        from incubator_mxnet_tpu import image as img_mod

        from incubator_mxnet_tpu.image.image import _PCA_EIGVAL, _PCA_EIGVEC

        src = self._img()
        np.random.seed(0)
        out = img_mod.LightingAug(10.0, _PCA_EIGVAL, _PCA_EIGVEC)(src).asnumpy()
        # per-pixel shift is constant across the image
        delta = out - src.asnumpy()
        assert np.allclose(delta, delta[0, 0], atol=1e-4)

        gray = img_mod.RandomGrayAug(1.0)(src).asnumpy()
        assert np.allclose(gray[..., 0], gray[..., 1])
        assert np.allclose(gray[..., 1], gray[..., 2])

        crop = img_mod.RandomSizedCropAug((16, 16), 0.3, (0.75, 1.333))(src)
        assert crop.shape == (16, 16, 3)

    def test_create_augmenter_full_surface(self):
        import random
        from incubator_mxnet_tpu import image as img_mod

        random.seed(0)
        np.random.seed(0)
        augs = img_mod.CreateAugmenter(
            (3, 24, 24), resize=28, rand_crop=True, rand_resize=True,
            rand_mirror=True, mean=True, std=True, brightness=0.2,
            contrast=0.2, saturation=0.2, hue=0.1, pca_noise=0.05,
            rand_gray=0.05)
        kinds = [type(a).__name__ for a in augs]
        assert "RandomSizedCropAug" in kinds and "ColorJitterAug" in kinds
        assert "HueJitterAug" in kinds and "LightingAug" in kinds
        src = self._img(40, 40)
        for a in augs:
            src = a(src)
        assert src.shape == (24, 24, 3)
        with pytest.raises(ValueError):
            img_mod.CreateAugmenter((3, 24, 24), rand_resize=True)

    def test_sequential_and_random_order(self):
        from incubator_mxnet_tpu import image as img_mod

        src = self._img()
        seq = img_mod.SequentialAug([img_mod.CastAug("float32"),
                                     img_mod.BrightnessJitterAug(0.0)])
        out = seq(src)
        np.testing.assert_allclose(out.asnumpy(), src.asnumpy(), rtol=1e-6)
        ro = img_mod.RandomOrderAug([img_mod.BrightnessJitterAug(0.0),
                                     img_mod.SaturationJitterAug(0.0)])
        np.testing.assert_allclose(ro(src).asnumpy(), src.asnumpy(), rtol=1e-5)


def test_transforms_hue_and_color_jitter():
    """transforms.RandomHue / RandomColorJitter (round-5 parity tail)."""
    from incubator_mxnet_tpu.gluon.data.vision import transforms as T

    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randint(0, 255, (8, 8, 3)).astype(np.uint8),
                    dtype="uint8")
    # hue=0 is the identity (exact inverse YIQ matrix + integer rounding)
    np.testing.assert_array_equal(T.RandomHue(0.0)(x).asnumpy(), x.asnumpy())
    out = T.RandomColorJitter(0.3, 0.3, 0.3, 0.1)(x)
    assert out.shape == x.shape and out.dtype == np.uint8
    # luma is preserved by a pure hue rotation (within rounding) — use
    # mid-range pixels so the [0,255] clip never engages
    mid = mx.nd.array(rng.randint(80, 176, (8, 8, 3)).astype(np.uint8),
                      dtype="uint8")
    coef = np.array([0.299, 0.587, 0.114], np.float32)
    h = T.RandomHue(0.2)(mid).asnumpy().astype(np.float32)
    np.testing.assert_allclose((h * coef).sum(-1),
                               (mid.asnumpy() * coef).sum(-1), atol=2.0)
