"""ONNX export/import (parity: [U:tests/python-pytest/onnx/]).

No onnx package exists in this environment, so correctness rests on three
legs: (1) round-trip — export a Symbol graph, import it back, bind both
and compare outputs; (2) wire-format validation — protoc --decode_raw
must parse the emitted bytes; (3) structural checks on the decoded model.
"""
import os
import shutil
import subprocess

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
import incubator_mxnet_tpu.symbol as S
from incubator_mxnet_tpu.contrib import onnx as onnx_mxnet


def _lenet():
    S.symbol._reset_naming()
    data = S.var("data")
    c1 = S.Convolution(data, kernel=(3, 3), num_filter=4, pad=(1, 1), name="c1")
    a1 = S.Activation(c1, act_type="relu", name="a1")
    p1 = S.Pooling(a1, kernel=(2, 2), stride=(2, 2), pool_type="max", name="p1")
    f1 = S.Flatten(p1, name="f1")
    fc1 = S.FullyConnected(f1, num_hidden=10, name="fc1")
    return S.softmax(fc1, name="sm1")


def _bind_forward(sym, params, data):
    exe = sym.simple_bind(data=data.shape)
    args = exe.arg_dict
    args["data"][:] = data
    for k, v in params.items():
        name = k.split(":", 1)[1] if ":" in k else k
        if name in args:
            args[name][:] = v.asnumpy() if hasattr(v, "asnumpy") else v
        elif name in exe.aux_dict:
            exe.aux_dict[name][:] = v.asnumpy() if hasattr(v, "asnumpy") else v
    return exe.forward(is_train=False)[0].asnumpy()


def _rand_params(sym, data_shape):
    rng = np.random.RandomState(0)
    shapes, _, aux_shapes = sym.infer_shape(data=data_shape)
    params = {}
    for name, shp in zip(sym.list_arguments(), shapes):
        if name != "data":
            params[name] = mx.nd.array(rng.randn(*shp).astype(np.float32) * 0.1)
    for name, shp in zip(sym.list_auxiliary_states(), aux_shapes):
        params[name] = mx.nd.array(np.abs(rng.randn(*shp)).astype(np.float32) * 0.1)
    return params


class TestOnnxRoundtrip:
    def test_lenet_roundtrip(self, tmp_path):
        sym = _lenet()
        data = np.random.RandomState(1).rand(2, 3, 8, 8).astype(np.float32)
        params = _rand_params(sym, data.shape)
        ref = _bind_forward(sym, params, data)

        f = str(tmp_path / "lenet.onnx")
        onnx_mxnet.export_model(sym, params, input_shape=data.shape,
                                onnx_file_path=f)
        sym2, arg2, aux2 = onnx_mxnet.import_model(f)
        arg2.update(aux2)
        out = _bind_forward(sym2, arg2, data)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    def test_mlp_with_elemwise_roundtrip(self, tmp_path):
        S.symbol._reset_naming()
        data = S.var("data")
        fc = S.FullyConnected(data, num_hidden=6, name="fc1")
        act = S.Activation(fc, act_type="tanh", name="t1")
        out_sym = S.broadcast_add(act, fc, name="add1")
        data_np = np.random.RandomState(2).rand(3, 5).astype(np.float32)
        params = _rand_params(out_sym, data_np.shape)
        ref = _bind_forward(out_sym, params, data_np)

        f = str(tmp_path / "mlp.onnx")
        onnx_mxnet.export_model(out_sym, params, input_shape=data_np.shape,
                                onnx_file_path=f)
        sym2, arg2, aux2 = onnx_mxnet.import_model(f)
        out = _bind_forward(sym2, arg2, data_np)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    def test_metadata(self, tmp_path):
        sym = _lenet()
        data_shape = (2, 3, 8, 8)
        params = _rand_params(sym, data_shape)
        f = str(tmp_path / "m.onnx")
        onnx_mxnet.export_model(sym, params, input_shape=data_shape,
                                onnx_file_path=f)
        meta = onnx_mxnet.get_model_metadata(f)
        assert meta["input_tensor_data"] == [("data", data_shape)]
        assert len(meta["output_tensor_data"]) == 1

    @pytest.mark.skipif(shutil.which("protoc") is None, reason="protoc not available")
    def test_wire_format_parses_with_protoc(self, tmp_path):
        """The emitted bytes must be valid protobuf: protoc --decode_raw is
        an independent parser that rejects malformed wire data."""
        sym = _lenet()
        data_shape = (1, 3, 8, 8)
        params = _rand_params(sym, data_shape)
        f = str(tmp_path / "w.onnx")
        onnx_mxnet.export_model(sym, params, input_shape=data_shape,
                                onnx_file_path=f)
        with open(f, "rb") as fh:
            proc = subprocess.run(["protoc", "--decode_raw"], stdin=fh,
                                  capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr[-500:]
        # field 7 = GraphProto must appear in the decode
        assert "7 {" in proc.stdout


def test_import_foreign_gemm_transB0(tmp_path):
    """Foreign models use Gemm(transB=0, alpha): the importer must
    normalize the weight to FullyConnected's (out, in) convention."""
    from incubator_mxnet_tpu.contrib.onnx import _proto as P

    rng = np.random.RandomState(3)
    w = rng.randn(5, 4).astype(np.float32)   # (in=5, out=4): transB=0 layout
    b = rng.randn(4).astype(np.float32)
    model = {"ir_version": 8, "opset": 13, "graph": {
        "name": "g", "node": [{
            "op_type": "Gemm", "name": "g1", "input": ["data", "W", "B"],
            "output": ["y"],
            "attribute": [
                {"name": "alpha", "type": P.ATTR_FLOAT, "f": 2.0},
                {"name": "beta", "type": P.ATTR_FLOAT, "f": 0.5},
                {"name": "transB", "type": P.ATTR_INT, "i": 0},
            ]}],
        "initializer": [
            {"name": "W", "dims": w.shape, "data_type": P.TP_FLOAT, "raw": w.tobytes()},
            {"name": "B", "dims": b.shape, "data_type": P.TP_FLOAT, "raw": b.tobytes()},
        ],
        "input": [{"name": "data", "elem_type": P.TP_FLOAT, "shape": (2, 5)}],
        "output": [{"name": "y", "elem_type": P.TP_FLOAT, "shape": ()}],
    }}
    f = str(tmp_path / "foreign.onnx")
    with open(f, "wb") as fh:
        fh.write(P.enc_model(model))
    sym, args, aux = onnx_mxnet.import_model(f)
    x = np.random.RandomState(4).rand(2, 5).astype(np.float32)
    out = _bind_forward(sym, args, x)
    np.testing.assert_allclose(out, 2.0 * (x @ w) + 0.5 * b, rtol=1e-5, atol=1e-6)


class TestRound3Converters:
    def test_deconv_upsample_roundtrip(self, tmp_path):
        """DCGAN-generator-shaped graph: ConvTranspose + BN + activations
        + nearest Resize survive export->import numerically."""
        S.symbol._reset_naming()
        data = S.var("data")
        d1 = S.Deconvolution(data, kernel=(4, 4), stride=(2, 2), pad=(1, 1),
                             num_filter=4, name="d1")
        b1 = S.BatchNorm(d1, name="b1")
        r1 = S.Activation(b1, act_type="relu", name="r1")
        u1 = S.UpSampling(r1, scale=2, sample_type="nearest", name="u1")
        out_sym = S.tanh(u1, name="t1")

        data_np = np.random.RandomState(3).rand(2, 3, 4, 4).astype(np.float32)
        params = _rand_params(out_sym, data_np.shape)
        ref = _bind_forward(out_sym, params, data_np)

        f = str(tmp_path / "gen.onnx")
        onnx_mxnet.export_model(out_sym, params, input_shape=data_np.shape,
                                onnx_file_path=f)
        sym2, arg2, aux2 = onnx_mxnet.import_model(f)
        arg2.update(aux2)
        out = _bind_forward(sym2, arg2, data_np)
        assert out.shape == (2, 4, 16, 16)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_transpose_scalar_unary_roundtrip(self, tmp_path):
        S.symbol._reset_naming()
        data = S.var("data")
        t = S.transpose(data, axes=(0, 2, 1), name="tr1")
        s = t * 0.5 + 2.0        # _mul_scalar, _plus_scalar
        s = 3.0 - s              # _rminus_scalar (reverse operand order)
        out_sym = S.exp(S.sqrt(S.abs(s, name="ab1"), name="sq1"), name="ex1")

        data_np = np.random.RandomState(4).rand(2, 3, 5).astype(np.float32)
        ref = _bind_forward(out_sym, {}, data_np)

        f = str(tmp_path / "misc.onnx")
        onnx_mxnet.export_model(out_sym, {}, input_shape=data_np.shape,
                                onnx_file_path=f)
        sym2, arg2, aux2 = onnx_mxnet.import_model(f)
        arg2.update(aux2)
        out = _bind_forward(sym2, arg2, data_np)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# model-scale roundtrips (VERDICT r3 item 7): full ResNet-50 and a BERT
# encoder — export, re-import, numeric equality at rtol 1e-5.
# ---------------------------------------------------------------------------


def _resnet50_sym():
    """Full ResNet-50-v1 bottleneck graph (3-4-6-3) in the Symbol API."""
    S.symbol._reset_naming()
    data = S.var("data")
    x = S.Convolution(data, kernel=(7, 7), stride=(2, 2), pad=(3, 3),
                      num_filter=64, no_bias=True, name="conv0")
    x = S.BatchNorm(x, name="bn0")
    x = S.Activation(x, act_type="relu", name="relu0")
    x = S.Pooling(x, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                  pool_type="max", name="pool0")

    def bottleneck(x, prefix, filters, stride, downsample):
        sc = x
        if downsample:
            sc = S.Convolution(x, kernel=(1, 1), stride=(stride, stride),
                               num_filter=filters * 4, no_bias=True,
                               name=f"{prefix}_scconv")
            sc = S.BatchNorm(sc, name=f"{prefix}_scbn")
        y = S.Convolution(x, kernel=(1, 1), num_filter=filters, no_bias=True,
                          name=f"{prefix}_conv1")
        y = S.BatchNorm(y, name=f"{prefix}_bn1")
        y = S.Activation(y, act_type="relu", name=f"{prefix}_relu1")
        y = S.Convolution(y, kernel=(3, 3), stride=(stride, stride),
                          pad=(1, 1), num_filter=filters, no_bias=True,
                          name=f"{prefix}_conv2")
        y = S.BatchNorm(y, name=f"{prefix}_bn2")
        y = S.Activation(y, act_type="relu", name=f"{prefix}_relu2")
        y = S.Convolution(y, kernel=(1, 1), num_filter=filters * 4,
                          no_bias=True, name=f"{prefix}_conv3")
        y = S.BatchNorm(y, name=f"{prefix}_bn3")
        y = S.broadcast_add(y, sc, name=f"{prefix}_add")
        return S.Activation(y, act_type="relu", name=f"{prefix}_out")

    for stage, (blocks, filters) in enumerate(
            [(3, 64), (4, 128), (6, 256), (3, 512)], start=1):
        for b in range(blocks):
            stride = 2 if (stage > 1 and b == 0) else 1
            x = bottleneck(x, f"s{stage}b{b}", filters, stride, b == 0)
    x = S.Pooling(x, global_pool=True, pool_type="avg", kernel=(1, 1),
                  name="gap")
    x = S.Flatten(x, name="flat")
    return S.FullyConnected(x, num_hidden=1000, name="fc1000")


def _bert_encoder_sym(units=64, heads=4, hidden=128, layers=2):
    """BERT-style encoder: embeddings + LN + [MHA + FFN] blocks, gelu,
    rank-preserving FCs, batched attention matmuls."""
    S.symbol._reset_naming()
    tokens = S.var("data")  # [B, T] int32
    x = S.Embedding(tokens, input_dim=50, output_dim=units, name="embed")
    x = S.LayerNorm(x, name="embed_ln")
    import math

    for i in range(layers):
        p = f"l{i}"
        q = S.FullyConnected(x, num_hidden=units, flatten=False, name=f"{p}_q")
        k = S.FullyConnected(x, num_hidden=units, flatten=False, name=f"{p}_k")
        v = S.FullyConnected(x, num_hidden=units, flatten=False, name=f"{p}_v")

        def heads_split(t, nme):
            t = S.reshape(t, shape=(0, -1, heads, units // heads), name=f"{nme}_r")
            return S.transpose(t, axes=(0, 2, 1, 3), name=f"{nme}_t")

        qh = heads_split(q, f"{p}_qh")
        kh = heads_split(k, f"{p}_kh")
        vh = heads_split(v, f"{p}_vh")
        kt = S.transpose(kh, axes=(0, 1, 3, 2), name=f"{p}_kT")
        scores = S.batch_dot(qh, kt, name=f"{p}_scores")
        scores = S._mul_scalar(scores, scalar=1.0 / math.sqrt(units // heads),
                               name=f"{p}_scale")
        probs = S.softmax(scores, axis=-1, name=f"{p}_probs")
        ctx = S.batch_dot(probs, vh, name=f"{p}_ctx")
        ctx = S.transpose(ctx, axes=(0, 2, 1, 3), name=f"{p}_ctxT")
        ctx = S.reshape(ctx, shape=(0, -1, units), name=f"{p}_merge")
        proj = S.FullyConnected(ctx, num_hidden=units, flatten=False,
                                name=f"{p}_proj")
        x = S.LayerNorm(S.broadcast_add(x, proj, name=f"{p}_res1"),
                        name=f"{p}_ln1")
        h = S.FullyConnected(x, num_hidden=hidden, flatten=False,
                             name=f"{p}_ffn1")
        h = S.LeakyReLU(h, act_type="gelu", name=f"{p}_gelu")
        h = S.FullyConnected(h, num_hidden=units, flatten=False,
                             name=f"{p}_ffn2")
        x = S.LayerNorm(S.broadcast_add(x, h, name=f"{p}_res2"),
                        name=f"{p}_ln2")
    return x


class TestModelScaleRoundtrip:
    def test_resnet50_roundtrip(self, tmp_path):
        sym = _resnet50_sym()
        data = np.random.RandomState(0).rand(1, 3, 64, 64).astype(np.float32)
        params = _rand_params(sym, data.shape)
        ref = _bind_forward(sym, params, data)
        path = str(tmp_path / "resnet50.onnx")
        onnx_mxnet.export_model(sym, params, input_shape=data.shape,
                                onnx_file_path=path)
        sym2, arg2, aux2 = onnx_mxnet.import_model(path)
        out = _bind_forward(sym2, {**arg2, **aux2}, data)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-4)

    def test_bert_encoder_roundtrip(self, tmp_path):
        sym = _bert_encoder_sym()
        rng = np.random.RandomState(1)
        data = rng.randint(0, 50, (2, 12)).astype(np.int32)
        # infer_shape needs the int input; _rand_params assumes float data —
        # inline a variant
        shapes, _, aux_shapes = sym.infer_shape(data=data.shape)
        params = {}
        for name, shp in zip(sym.list_arguments(), shapes):
            if name != "data":
                params[name] = mx.nd.array(rng.randn(*shp).astype(np.float32) * 0.1)

        def fwd(s, ps):
            # no bind-time shape hints: the importer reconstructs
            # FullyConnected from the Transpose(W)→MatMul idiom, so weight
            # shapes infer from the graph like any native symbol
            exe = s.simple_bind(data=data.shape)
            exe.arg_dict["data"][:] = data
            for kk, vv in ps.items():
                nm2 = kk.split(":", 1)[1] if ":" in kk else kk
                if nm2 in exe.arg_dict:
                    exe.arg_dict[nm2][:] = vv.asnumpy()
                elif nm2 in exe.aux_dict:
                    exe.aux_dict[nm2][:] = vv.asnumpy()
            return exe.forward(is_train=False)[0].asnumpy()

        ref = fwd(sym, params)
        path = str(tmp_path / "bert_encoder.onnx")
        onnx_mxnet.export_model(sym, params, input_shape=data.shape,
                                input_type=np.int32, onnx_file_path=path)
        sym2, arg2, aux2 = onnx_mxnet.import_model(path)
        out = fwd(sym2, {**arg2, **aux2})
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def _foreign_model(tmp_path, nodes, inits, in_shape, name="foreign"):
    """Assemble a hand-built ONNX model through the wire codec (the
    foreign-model import fixture)."""
    from incubator_mxnet_tpu.contrib.onnx import _proto as P

    model = {"ir_version": 8, "opset": 13, "graph": {
        "name": "g", "node": nodes,
        "initializer": [
            {"name": k, "dims": v.shape,
             "data_type": P.DTYPE_TO_TP[np.dtype(v.dtype)],
             "raw": np.ascontiguousarray(v).tobytes()}
            for k, v in inits.items()],
        "input": [{"name": "data", "elem_type": P.TP_FLOAT, "shape": in_shape}],
        "output": [{"name": "y", "elem_type": P.TP_FLOAT, "shape": ()}],
    }}
    f = str(tmp_path / f"{name}.onnx")
    with open(f, "wb") as fh:
        fh.write(P.enc_model(model))
    return f


class TestForeignImportBreadth:
    """Importers for common foreign-model ops (Clip/Pad/Reduce*/Squeeze/
    Unsqueeze/Cast/Identity), each against numpy."""

    def test_clip_input_form_and_identity(self, tmp_path):
        lo = np.array(-0.5, np.float32)
        hi = np.array(1.0, np.float32)
        f = _foreign_model(tmp_path, [
            {"op_type": "Clip", "name": "c", "input": ["data", "lo", "hi"],
             "output": ["c0"], "attribute": []},
            {"op_type": "Identity", "name": "i", "input": ["c0"],
             "output": ["y"], "attribute": []},
        ], {"lo": lo, "hi": hi}, (2, 4))
        sym, args, aux = onnx_mxnet.import_model(f)
        x = np.random.RandomState(0).randn(2, 4).astype(np.float32)
        out = _bind_forward(sym, args, x)
        np.testing.assert_allclose(out, np.clip(x, -0.5, 1.0), rtol=1e-6)

    def test_pad_input_form(self, tmp_path):
        from incubator_mxnet_tpu.contrib.onnx import _proto as P

        pads = np.array([0, 0, 1, 1, 0, 0, 1, 1], np.int64)  # H/W by 1
        f = _foreign_model(tmp_path, [
            {"op_type": "Pad", "name": "p", "input": ["data", "pads"],
             "output": ["y"],
             "attribute": [{"name": "mode", "type": P.ATTR_STRING,
                            "s": b"edge"}]},
        ], {"pads": pads}, (1, 2, 3, 3))
        sym, args, aux = onnx_mxnet.import_model(f)
        x = np.random.RandomState(1).rand(1, 2, 3, 3).astype(np.float32)
        out = _bind_forward(sym, args, x)
        np.testing.assert_allclose(out, np.pad(x, [(0, 0), (0, 0), (1, 1), (1, 1)],
                                               mode="edge"), rtol=1e-6)

    def test_reduce_mean_and_sum13(self, tmp_path):
        from incubator_mxnet_tpu.contrib.onnx import _proto as P

        axes = np.array([1], np.int64)
        f = _foreign_model(tmp_path, [
            {"op_type": "ReduceMean", "name": "m", "input": ["data"],
             "output": ["m0"],
             "attribute": [{"name": "axes", "type": P.ATTR_INTS, "ints": [2]},
                           {"name": "keepdims", "type": P.ATTR_INT, "i": 0}]},
            {"op_type": "ReduceSum", "name": "s", "input": ["m0", "ax"],
             "output": ["y"],
             "attribute": [{"name": "keepdims", "type": P.ATTR_INT, "i": 1}]},
        ], {"ax": axes}, (2, 3, 4))
        sym, args, aux = onnx_mxnet.import_model(f)
        x = np.random.RandomState(2).rand(2, 3, 4).astype(np.float32)
        out = _bind_forward(sym, args, x)
        np.testing.assert_allclose(out, x.mean(2).sum(1, keepdims=True),
                                   rtol=1e-5, atol=1e-6)

    def test_squeeze_unsqueeze_cast(self, tmp_path):
        from incubator_mxnet_tpu.contrib.onnx import _proto as P

        f = _foreign_model(tmp_path, [
            {"op_type": "Unsqueeze", "name": "u", "input": ["data"],
             "output": ["u0"],
             "attribute": [{"name": "axes", "type": P.ATTR_INTS, "ints": [0, 3]}]},
            {"op_type": "Squeeze", "name": "q", "input": ["u0"],
             "output": ["q0"],
             "attribute": [{"name": "axes", "type": P.ATTR_INTS, "ints": [0]}]},
            {"op_type": "Cast", "name": "k", "input": ["q0"], "output": ["y"],
             "attribute": [{"name": "to", "type": P.ATTR_INT,
                            "i": P.TP_FLOAT}]},
        ], {}, (2, 5))
        sym, args, aux = onnx_mxnet.import_model(f)
        x = np.random.RandomState(3).rand(2, 5).astype(np.float32)
        out = _bind_forward(sym, args, x)
        np.testing.assert_allclose(out, x[:, :, None], rtol=1e-6)


# ===========================================================================
# RNN family (LSTM/GRU/RNN) export + import
# ===========================================================================


def _rnn_sym_and_params(mode, C, H, L, bidir, seed=0, explicit_states=False,
                        B=3):
    """Build a sym.RNN graph plus random packed params; with
    ``explicit_states`` the zero initial states are bound initializers
    (exercising the exporter's drop-zero-states path) instead of omitted."""
    from incubator_mxnet_tpu.ops.rnn_ops import rnn_param_size

    S.symbol._reset_naming()
    D = 2 if bidir else 1
    data = S.var("data")
    p = S.var("rnn_parameters")
    rng = np.random.RandomState(seed)
    n = rnn_param_size(mode, C, H, L, bidir)
    params = {"rnn_parameters": mx.nd.array(
        rng.uniform(-0.4, 0.4, (n,)).astype(np.float32))}
    ins = [data, p]
    if explicit_states:
        ins.append(S.var("rnn_state"))
        params["rnn_state"] = mx.nd.array(np.zeros((L * D, B, H), np.float32))
        if mode == "lstm":
            ins.append(S.var("rnn_state_cell"))
            params["rnn_state_cell"] = mx.nd.array(
                np.zeros((L * D, B, H), np.float32))
    out = S.RNN(*ins, mode=mode, state_size=H, num_layers=L,
                bidirectional=bidir, name="rnn0")
    return out, params


def _bind_rnn(sym, params, data, B, H, L, D, lstm):
    exe = sym.simple_bind(data=data.shape)
    args = exe.arg_dict
    args["data"][:] = data
    for k, v in params.items():
        if k in args:
            args[k][:] = v.asnumpy()
    # zero states (present as args)
    return exe.forward(is_train=False)[0].asnumpy()


class TestOnnxRNNFamily:
    @pytest.mark.parametrize("mode,bidir,L,explicit", [
        ("lstm", False, 1, False), ("lstm", True, 2, True),
        ("gru", True, 1, False), ("rnn_relu", False, 2, False),
        ("rnn_tanh", True, 1, True)])
    def test_rnn_roundtrip(self, tmp_path, mode, bidir, L, explicit):
        T, B, C, H = 5, 3, 4, 6
        D = 2 if bidir else 1
        sym, params = _rnn_sym_and_params(mode, C, H, L, bidir,
                                          explicit_states=explicit, B=B)
        data = np.random.RandomState(1).uniform(-1, 1, (T, B, C)).astype(np.float32)
        ref = _bind_rnn(sym, params, data, B, H, L, D, mode == "lstm")
        assert ref.shape == (T, B, D * H)

        f = str(tmp_path / f"{mode}.onnx")
        onnx_mxnet.export_model(sym, params, input_shape=data.shape,
                                onnx_file_path=f)
        sym2, arg2, aux2 = onnx_mxnet.import_model(f)
        arg2.update(aux2)
        out = _bind_forward(sym2, arg2, data)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    def test_lstm_encoder_roundtrip(self, tmp_path):
        """2-layer LSTM encoder over an embedding, dense head — the
        seq2seq-encoder shape the VERDICT names, at rtol 1e-5."""
        from incubator_mxnet_tpu.ops.rnn_ops import rnn_param_size

        S.symbol._reset_naming()
        T, B, V, E, H = 6, 2, 50, 8, 10
        rng = np.random.RandomState(3)
        tok = S.var("data")  # [T, B] int tokens
        emb = S.Embedding(tok, S.var("embed_weight"), input_dim=V,
                          output_dim=E, name="embed")
        p = S.var("enc_parameters")
        enc = S.RNN(emb, p, mode="lstm", state_size=H, num_layers=2,
                    name="enc")
        head = S.FullyConnected(enc, S.var("head_weight"), S.var("head_bias"),
                                num_hidden=4, flatten=False, name="head")
        n = rnn_param_size("lstm", E, H, 2, False)
        params = {
            "embed_weight": mx.nd.array(rng.randn(V, E).astype(np.float32) * 0.1),
            "enc_parameters": mx.nd.array(
                rng.uniform(-0.3, 0.3, (n,)).astype(np.float32)),
            "head_weight": mx.nd.array(rng.randn(4, H).astype(np.float32) * 0.1),
            "head_bias": mx.nd.array(rng.randn(4).astype(np.float32) * 0.1),
        }
        data = rng.randint(0, V, (T, B)).astype(np.int64)

        exe = head.simple_bind(data=data.shape)
        exe.arg_dict["data"][:] = data
        for k, v in params.items():
            exe.arg_dict[k][:] = v.asnumpy()
        ref = exe.forward(is_train=False)[0].asnumpy()

        f = str(tmp_path / "encoder.onnx")
        onnx_mxnet.export_model(head, params, input_shape=data.shape,
                                onnx_file_path=f)
        sym2, arg2, aux2 = onnx_mxnet.import_model(f)
        arg2.update(aux2)
        exe2 = sym2.simple_bind(data=data.shape)
        exe2.arg_dict["data"][:] = data
        for k, v in arg2.items():
            if k in exe2.arg_dict and k != "data":
                exe2.arg_dict[k][:] = v.asnumpy()
        out = exe2.forward(is_train=False)[0].asnumpy()
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    def test_rnn_export_rejections(self, tmp_path):
        # non-zero initial state must be rejected, not mistranslated
        sym, params = _rnn_sym_and_params("lstm", 3, 4, 1, False,
                                          explicit_states=True, B=2)
        params["rnn_state"] = mx.nd.array(np.ones((1, 2, 4), np.float32))
        with pytest.raises(NotImplementedError):
            onnx_mxnet.export_model(sym, params, input_shape=(5, 2, 3),
                                    onnx_file_path=str(tmp_path / "x.onnx"))

    def test_foreign_gru_linear_before_reset0_rejected(self, tmp_path):
        from incubator_mxnet_tpu.contrib.onnx import _proto as P

        H, C = 4, 3
        W = np.random.RandomState(0).randn(1, 3 * H, C).astype(np.float32)
        R = np.random.RandomState(1).randn(1, 3 * H, H).astype(np.float32)
        f = _foreign_model(tmp_path, [
            {"op_type": "GRU", "name": "g0", "input": ["data", "W", "R"],
             "output": ["y"],
             "attribute": [{"name": "hidden_size", "type": P.ATTR_INT, "i": H}]},
        ], {"W": W, "R": R}, (5, 2, C))
        with pytest.raises(NotImplementedError):
            onnx_mxnet.import_model(f)

    def test_foreign_lstm_no_bias_import(self, tmp_path):
        """A hand-built single LSTM node without B input: import must
        zero-fill the bias and produce the right-shaped output."""
        from incubator_mxnet_tpu.contrib.onnx import _proto as P

        T, B, C, H = 4, 2, 3, 5
        rng = np.random.RandomState(0)
        W = rng.randn(1, 4 * H, C).astype(np.float32) * 0.3
        R = rng.randn(1, 4 * H, H).astype(np.float32) * 0.3
        f = _foreign_model(tmp_path, [
            {"op_type": "LSTM", "name": "l0", "input": ["data", "W", "R"],
             "output": ["Y"], "attribute": [
                 {"name": "hidden_size", "type": P.ATTR_INT, "i": H}]},
            {"op_type": "Transpose", "name": "t", "input": ["Y"],
             "output": ["yt"],
             "attribute": [{"name": "perm", "type": P.ATTR_INTS,
                            "ints": [0, 2, 1, 3]}]},
            {"op_type": "Reshape", "name": "r", "input": ["yt", "shp"],
             "output": ["y"], "attribute": []},
        ], {"W": W, "R": R, "shp": np.asarray([0, 0, -1], np.int64)},
            (T, B, C))
        sym, args, aux = onnx_mxnet.import_model(f)
        x = rng.uniform(-1, 1, (T, B, C)).astype(np.float32)
        out = _bind_forward(sym, args, x)
        assert out.shape == (T, B, H)
        # independent check: numpy LSTM with ONNX gate order [i,o,f,c]
        h = np.zeros((B, H), np.float32)
        c = np.zeros((B, H), np.float32)
        sig = lambda v: 1.0 / (1.0 + np.exp(-v))
        want = np.zeros((T, B, H), np.float32)
        for t in range(T):
            gates = x[t] @ W[0].T + h @ R[0].T
            i = sig(gates[:, 0:H])
            o = sig(gates[:, H:2*H])
            fgt = sig(gates[:, 2*H:3*H])
            cc = np.tanh(gates[:, 3*H:4*H])
            c = fgt * c + i * cc
            h = o * np.tanh(c)
            want[t] = h
        np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


def test_detection_graph_export_documented_rejection(tmp_path):
    """MultiBox/NMS graphs must be rejected with guidance, not silently
    mistranslated (dynamic ONNX NonMaxSuppression vs static padded
    layouts)."""
    S.symbol._reset_naming()
    data = S.var("data")
    prior = S.contrib_MultiBoxPrior(data, sizes=(0.5,), ratios=(1.0,),
                                    name="prior")
    with pytest.raises(NotImplementedError, match="detection post-processing"):
        onnx_mxnet.export_model(prior, {}, input_shape=(1, 3, 8, 8),
                                onnx_file_path=str(tmp_path / "d.onnx"))


class TestForeignImportBreadth2:
    """Round-5 foreign-op importers: Constant folding, Slice, Split,
    Gather(axis), Pow, Expand, Where/Equal."""

    def test_constant_slice_pow(self, tmp_path):
        from incubator_mxnet_tpu.contrib.onnx import _proto as P

        cval = np.asarray([2.0], np.float32)
        f = _foreign_model(tmp_path, [
            {"op_type": "Constant", "name": "c", "input": [], "output": ["cv"],
             "attribute": [{"name": "value", "type": P.ATTR_TENSOR,
                            "t": {"name": "", "dims": cval.shape,
                                  "data_type": P.TP_FLOAT,
                                  "raw": cval.tobytes()}}]},
            {"op_type": "Slice", "name": "s", "input": ["data"],
             "output": ["s0"],
             "attribute": [
                 {"name": "starts", "type": P.ATTR_INTS, "ints": [1]},
                 {"name": "ends", "type": P.ATTR_INTS, "ints": [3]},
                 {"name": "axes", "type": P.ATTR_INTS, "ints": [1]}]},
            {"op_type": "Pow", "name": "p", "input": ["s0", "pw"],
             "output": ["p0"], "attribute": []},
            {"op_type": "Mul", "name": "m", "input": ["p0", "cv"],
             "output": ["y"], "attribute": []},
        ], {"pw": np.asarray(2.0, np.float32)}, (2, 5))
        sym2, args, aux = onnx_mxnet.import_model(f)
        x = np.random.RandomState(0).rand(2, 5).astype(np.float32)
        out = _bind_forward(sym2, args, x)
        np.testing.assert_allclose(out, (x[:, 1:3] ** 2) * 2.0, rtol=1e-5,
                                   atol=1e-6)

    def test_slice_input_form_with_intmax_end(self, tmp_path):
        from incubator_mxnet_tpu.contrib.onnx import _proto as P

        f = _foreign_model(tmp_path, [
            {"op_type": "Slice", "name": "s",
             "input": ["data", "st", "en", "ax"], "output": ["y"],
             "attribute": []},
        ], {"st": np.asarray([1], np.int64),
            "en": np.asarray([2 ** 31 - 1], np.int64),  # "to the end" idiom
            "ax": np.asarray([0], np.int64)}, (4, 3))
        sym2, args, aux = onnx_mxnet.import_model(f)
        x = np.random.RandomState(1).rand(4, 3).astype(np.float32)
        out = _bind_forward(sym2, args, x)
        np.testing.assert_allclose(out, x[1:], rtol=1e-6)

    def test_split_equal_and_unequal(self, tmp_path):
        from incubator_mxnet_tpu.contrib.onnx import _proto as P

        # equal split -> Add recombines
        f = _foreign_model(tmp_path, [
            {"op_type": "Split", "name": "sp", "input": ["data"],
             "output": ["a", "b"],
             "attribute": [{"name": "axis", "type": P.ATTR_INT, "i": 1}]},
            {"op_type": "Add", "name": "ad", "input": ["a", "b"],
             "output": ["y"], "attribute": []},
        ], {}, (2, 6))
        sym2, args, aux = onnx_mxnet.import_model(f)
        x = np.random.RandomState(2).rand(2, 6).astype(np.float32)
        out = _bind_forward(sym2, args, x)
        np.testing.assert_allclose(out, x[:, :3] + x[:, 3:], rtol=1e-6)

        # unequal split sizes via input
        f2 = _foreign_model(tmp_path, [
            {"op_type": "Split", "name": "sp", "input": ["data", "sz"],
             "output": ["a", "b"],
             "attribute": [{"name": "axis", "type": P.ATTR_INT, "i": 1}]},
            {"op_type": "Concat", "name": "cc", "input": ["b", "a"],
             "output": ["y"],
             "attribute": [{"name": "axis", "type": P.ATTR_INT, "i": 1}]},
        ], {"sz": np.asarray([2, 4], np.int64)}, (2, 6), name="f2")
        sym3, args3, _ = onnx_mxnet.import_model(f2)
        out = _bind_forward(sym3, args3, x)
        np.testing.assert_allclose(
            out, np.concatenate([x[:, 2:], x[:, :2]], axis=1), rtol=1e-6)

    def test_gather_axis_expand_where_equal(self, tmp_path):
        from incubator_mxnet_tpu.contrib.onnx import _proto as P

        idx = np.asarray([2, 0], np.int64)
        f = _foreign_model(tmp_path, [
            # Gather over axis 1 of the data input (NOT the embedding idiom)
            {"op_type": "Gather", "name": "g", "input": ["data", "idx"],
             "output": ["g0"],
             "attribute": [{"name": "axis", "type": P.ATTR_INT, "i": 1}]},
            {"op_type": "Equal", "name": "e", "input": ["g0", "g0"],
             "output": ["m"], "attribute": []},
            {"op_type": "Where", "name": "w", "input": ["m", "g0", "zz"],
             "output": ["w0"], "attribute": []},
            {"op_type": "Expand", "name": "x", "input": ["w0", "shp"],
             "output": ["y"], "attribute": []},
        ], {"idx": idx, "zz": np.zeros((2, 2), np.float32),
            "shp": np.asarray([2, 2], np.int64)}, (2, 4))
        sym2, args, aux = onnx_mxnet.import_model(f)
        x = np.random.RandomState(3).rand(2, 4).astype(np.float32)
        out = _bind_forward(sym2, args, x)
        np.testing.assert_allclose(out, x[:, [2, 0]], rtol=1e-6)


def test_symbol_split_multi_output_api():
    """sym.split now carries num_outputs outputs (the MXNet contract)."""
    S.symbol._reset_naming()
    x = S.var("data")
    parts = S.split(x, num_outputs=3, axis=1)
    assert len(parts) == 3
    y = S.broadcast_add(parts[0], parts[2])
    exe = y.simple_bind(data=(2, 6))
    xv = np.arange(12).reshape(2, 6).astype(np.float32)
    exe.arg_dict["data"][:] = xv
    out = exe.forward(is_train=False)[0].asnumpy()
    np.testing.assert_allclose(out, xv[:, :2] + xv[:, 4:], rtol=1e-6)


# ---------------------------------------------------------------------------
# round-5 breadth 3: shape/reduce/elementwise/normalization converter
# families, both directions ([U:python/mxnet/contrib/onnx/mx2onnx/
# _op_translations.py] families not yet covered)
# ---------------------------------------------------------------------------


class TestOnnxBreadth3:
    def _roundtrip(self, tmp_path, out_sym, data_np, params=None, rtol=1e-5,
                   atol=1e-6):
        params = params or {}
        ref = _bind_forward(out_sym, params, data_np)
        f = str(tmp_path / "b3.onnx")
        onnx_mxnet.export_model(out_sym, params, input_shape=data_np.shape,
                                onnx_file_path=f)
        sym2, arg2, aux2 = onnx_mxnet.import_model(f)
        arg2.update(aux2)
        out = _bind_forward(sym2, arg2, data_np)
        np.testing.assert_allclose(out, ref, rtol=rtol, atol=atol)
        return out

    def test_shape_family_roundtrip(self, tmp_path):
        """slice / slice_axis / squeeze / expand_dims / tile / pad chain."""
        S.symbol._reset_naming()
        data = S.var("data")
        x = S.slice(data, begin=(0, 1), end=(2, None), name="sl1")
        x = S.slice_axis(x, axis=1, begin=0, end=2, name="sa1")
        x = S.expand_dims(x, axis=1, name="ed1")
        x = S.tile(x, reps=(2, 1, 1), name="ti1")  # reps rank == input rank
        x = S.pad(x, mode="constant", pad_width=(0, 0, 0, 0, 1, 1),
                  constant_value=0.5, name="pd1")
        out_sym = S.squeeze(S.slice_axis(x, axis=1, begin=0, end=1, name="sa2"),
                            axis=1, name="sq1")
        data_np = np.random.RandomState(5).rand(3, 4).astype(np.float32)
        out = self._roundtrip(tmp_path, out_sym, data_np)
        assert out.shape == (4, 4)

    def test_reduce_argmax_roundtrip(self, tmp_path):
        S.symbol._reset_naming()
        data = S.var("data")
        m = S.mean(data, axis=2, name="me1")
        s = S.sum(m, axis=(1,), keepdims=True, name="su1")  # ReduceSum-13 axes input
        am = S.argmax(data, axis=1, keepdims=True, name="am1")
        out_sym = S.broadcast_add(s, S.max(am, axis=1, keepdims=True, name="mx1"),
                                  name="out1")
        data_np = np.random.RandomState(6).rand(2, 3, 4).astype(np.float32)
        self._roundtrip(tmp_path, out_sym, data_np)

    def test_unary_elemwise_breadth_roundtrip(self, tmp_path):
        S.symbol._reset_naming()
        data = S.var("data")
        x = S.clip(data, a_min=-0.8, a_max=0.8, name="cl1")
        x = S.sin(x, name="si1") + S.cos(x, name="co1")
        x = S.floor(x * 3.0) + S.ceil(x * 2.0) + S.sign(x, name="sg1")
        x = S.broadcast_maximum(x, S.broadcast_minimum(x * 0.5, x * 0.25,
                                                       name="mi1"), name="ma1")
        out_sym = S.reciprocal(x + 4.0, name="re1")
        data_np = (np.random.RandomState(7).rand(2, 5).astype(np.float32) - 0.5)
        self._roundtrip(tmp_path, out_sym, data_np)

    def test_where_cast_roundtrip(self, tmp_path):
        S.symbol._reset_naming()
        data = S.var("data")
        cond = S.floor(S.clip(data * 2.0, a_min=0.0, a_max=1.0, name="cc1"),
                       name="fl1")
        w = S.where(cond, data * 2.0, data - 1.0, name="wh1")
        out_sym = S.cast(w, dtype="float32", name="ca1")
        data_np = (np.random.RandomState(8).rand(3, 4).astype(np.float32) - 0.3)
        self._roundtrip(tmp_path, out_sym, data_np)

    def test_onehot_logsoftmax_roundtrip(self, tmp_path):
        S.symbol._reset_naming()
        data = S.var("data")
        idx = S.argmax(data, axis=1, name="am1")          # float indices
        oh = S.one_hot(idx, depth=3, on_value=2.0, off_value=-1.0, name="oh1")
        out_sym = S.log_softmax(oh, axis=-1, name="ls1")
        data_np = np.random.RandomState(9).rand(4, 3).astype(np.float32)
        self._roundtrip(tmp_path, out_sym, data_np)

    def test_instance_norm_l2norm_roundtrip(self, tmp_path):
        S.symbol._reset_naming()
        data = S.var("data")
        inorm = S.InstanceNorm(data, S.var("g1"), S.var("b1"), eps=1e-3,
                               name="in1")
        out_sym = S.L2Normalization(inorm, mode="channel", name="l2n1")
        data_np = np.random.RandomState(10).rand(2, 3, 4, 4).astype(np.float32)
        params = {"g1": mx.nd.array(np.array([1.0, 2.0, 0.5], np.float32)),
                  "b1": mx.nd.array(np.array([0.1, -0.2, 0.0], np.float32))}
        self._roundtrip(tmp_path, out_sym, data_np, params=params, rtol=1e-4,
                        atol=1e-5)

    def test_softmax_output_inference_export(self, tmp_path):
        S.symbol._reset_naming()
        data = S.var("data")
        fc = S.FullyConnected(data, num_hidden=5, name="fc1")
        out_sym = S.SoftmaxOutput(fc, S.var("label"), name="so1")
        data_np = np.random.RandomState(11).rand(3, 4).astype(np.float32)
        params = _rand_params(out_sym, data_np.shape)
        params = {k: v for k, v in params.items() if k != "label"}
        ref = _bind_forward(out_sym, params, data_np)
        f = str(tmp_path / "so.onnx")
        onnx_mxnet.export_model(out_sym, params, input_shape=data_np.shape,
                                onnx_file_path=f)
        sym2, arg2, aux2 = onnx_mxnet.import_model(f)
        arg2.update(aux2)
        out = _bind_forward(sym2, arg2, data_np)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    def test_export_rejections(self, tmp_path):
        S.symbol._reset_naming()
        data = S.var("data")
        for bad in (S.round(data, name="ro1"),
                    S.argmax(data, name="am0"),          # axis=None
                    S.L2Normalization(data, mode="instance", name="l2i"),
                    S.sum(data, axis=1, exclude=True, name="sx1")):
            with pytest.raises(NotImplementedError):
                onnx_mxnet.export_model(bad, {}, input_shape=(2, 3),
                                        onnx_file_path=str(tmp_path / "x.onnx"))

    def test_foreign_variadic_max_logical(self, tmp_path):
        from incubator_mxnet_tpu.contrib.onnx import _proto as P

        f = _foreign_model(tmp_path, [
            {"op_type": "Max", "name": "m", "input": ["data", "c1", "c2"],
             "output": ["m0"], "attribute": []},
            {"op_type": "Greater", "name": "g", "input": ["m0", "c1"],
             "output": ["g0"], "attribute": []},
            {"op_type": "Not", "name": "n", "input": ["g0"],
             "output": ["n0"], "attribute": []},
            {"op_type": "Or", "name": "o", "input": ["n0", "g0"],
             "output": ["y"], "attribute": []},
        ], {"c1": np.full((2, 3), 0.5, np.float32),
            "c2": np.full((2, 3), 0.25, np.float32)}, (2, 3))
        sym2, args, aux = onnx_mxnet.import_model(f)
        x = np.random.RandomState(12).rand(2, 3).astype(np.float32)
        out = _bind_forward(sym2, args, x)
        np.testing.assert_allclose(out, np.ones((2, 3), np.float32), rtol=1e-6)

    def test_foreign_tile_onehot_argmax(self, tmp_path):
        from incubator_mxnet_tpu.contrib.onnx import _proto as P

        f = _foreign_model(tmp_path, [
            {"op_type": "ArgMax", "name": "a", "input": ["data"],
             "output": ["a0"],
             "attribute": [{"name": "axis", "type": P.ATTR_INT, "i": 1},
                           {"name": "keepdims", "type": P.ATTR_INT, "i": 0}]},
            {"op_type": "OneHot", "name": "h", "input": ["a0", "dep", "val"],
             "output": ["h0"],
             "attribute": [{"name": "axis", "type": P.ATTR_INT, "i": -1}]},
            {"op_type": "Tile", "name": "t", "input": ["h0", "rep"],
             "output": ["y"], "attribute": []},
        ], {"dep": np.asarray(3, np.int64),
            "val": np.asarray([0.0, 1.0], np.float32),
            "rep": np.asarray([2, 1], np.int64)}, (2, 3))
        sym2, args, aux = onnx_mxnet.import_model(f)
        x = np.random.RandomState(13).rand(2, 3).astype(np.float32)
        out = _bind_forward(sym2, args, x)
        expect = np.eye(3, dtype=np.float32)[x.argmax(1)]
        np.testing.assert_allclose(out, np.tile(expect, (2, 1)), rtol=1e-6)
