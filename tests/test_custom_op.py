"""Custom-op + profiler tests (parity idioms: test_operator.py's
CustomOp cases and test_profiler.py in the reference)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import profiler


@mx.operator.register("mysigmoid")
class SigmoidProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return Sigmoid()


class Sigmoid(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        self.assign(out_data[0], req[0], mx.nd.array(1.0 / (1.0 + np.exp(-x))))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0].asnumpy()
        g = out_grad[0].asnumpy()
        self.assign(in_grad[0], req[0], mx.nd.array(g * y * (1.0 - y)))


@mx.operator.register("myclip2")
class TwoOutProp(mx.operator.CustomOpProp):
    def list_outputs(self):
        return ["pos", "neg"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0], in_shape[0]], []

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return TwoOut()


class TwoOut(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        self.assign(out_data[0], req[0], mx.nd.array(np.maximum(x, 0)))
        self.assign(out_data[1], req[1], mx.nd.array(np.minimum(x, 0)))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        x = in_data[0].asnumpy()
        g = (out_grad[0].asnumpy() * (x > 0) + out_grad[1].asnumpy() * (x <= 0))
        self.assign(in_grad[0], req[0], mx.nd.array(g))


class TestCustomOp:
    def test_forward(self):
        x = mx.nd.array(np.array([-1.0, 0.0, 2.0], np.float32))
        y = mx.nd.Custom(x, op_type="mysigmoid")
        np.testing.assert_allclose(y.asnumpy(), 1 / (1 + np.exp([1.0, 0.0, -2.0])),
                                   rtol=1e-6)

    def test_backward_matches_analytic(self):
        rng = np.random.RandomState(0)
        xv = rng.randn(4, 5).astype(np.float32)
        x = mx.nd.array(xv)
        x.attach_grad()
        with mx.autograd.record():
            y = mx.nd.Custom(x, op_type="mysigmoid")
            loss = mx.nd.sum(y * y)
        loss.backward()
        s = 1 / (1 + np.exp(-xv))
        np.testing.assert_allclose(x.grad.asnumpy(), 2 * s * s * (1 - s),
                                   rtol=1e-5, atol=1e-6)

    def test_composes_with_builtin_ops_on_tape(self):
        x = mx.nd.array(np.array([0.5, -0.5], np.float32))
        x.attach_grad()
        with mx.autograd.record():
            h = x * 3.0
            y = mx.nd.Custom(h, op_type="mysigmoid")
            loss = mx.nd.sum(y)
        loss.backward()
        s = 1 / (1 + np.exp(-3 * np.array([0.5, -0.5])))
        np.testing.assert_allclose(x.grad.asnumpy(), 3 * s * (1 - s), rtol=1e-5)

    def test_multi_output(self):
        x = mx.nd.array(np.array([1.0, -2.0, 3.0], np.float32))
        x.attach_grad()
        with mx.autograd.record():
            pos, neg = mx.nd.Custom(x, op_type="myclip2")
            loss = mx.nd.sum(pos * 2.0) + mx.nd.sum(neg * 5.0)
        loss.backward()
        np.testing.assert_allclose(pos.asnumpy(), [1.0, 0.0, 3.0])
        np.testing.assert_allclose(neg.asnumpy(), [0.0, -2.0, 0.0])
        np.testing.assert_allclose(x.grad.asnumpy(), [2.0, 5.0, 2.0])

    def test_inside_jit_via_symbol(self):
        from incubator_mxnet_tpu import sym
        data = sym.Variable("data")
        out = sym.Custom(data, op_type="mysigmoid", name="cs")
        ex = out.bind(mx.cpu(), args={"data": mx.nd.array(np.zeros((2, 2), np.float32))},
                      grad_req="null")
        res = ex.forward(is_train=False)
        np.testing.assert_allclose(res[0].asnumpy(), np.full((2, 2), 0.5), rtol=1e-6)

    def test_unregistered_raises(self):
        with pytest.raises(KeyError, match="not registered"):
            mx.nd.Custom(mx.nd.ones((2,)), op_type="nope")


class TestProfiler:
    def test_scope_and_dumps(self):
        profiler.set_config(filename="/tmp/prof_test/profile.json")
        with profiler.scope("work"):
            (mx.nd.ones((64, 64)) @ mx.nd.ones((64, 64))).asnumpy()
        s = profiler.dumps()
        assert "work" in s

    def test_start_stop_cycle(self, tmp_path):
        profiler.set_config(filename=str(tmp_path / "profile.json"))
        profiler.start()
        (mx.nd.ones((32, 32)) * 2).asnumpy()
        profiler.stop()
        assert profiler.state() == "stopped"
        profiler.dump()
