"""Autograd tape semantics (parity model: [U:tests/python/unittest/test_autograd.py])."""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import autograd
from incubator_mxnet_tpu.utils.test_utils import assert_almost_equal, check_numeric_gradient

from common import with_seed


def test_record_backward_simple():
    x = mx.nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    assert_almost_equal(x.grad, 2 * x.asnumpy())


def test_chain_rule():
    x = mx.nd.array([[0.5, -0.5], [1.0, 2.0]])
    x.attach_grad()
    with autograd.record():
        y = mx.nd.exp(x) * 2
        z = (y + x).sum()
    z.backward()
    assert_almost_equal(x.grad, 2 * np.exp(x.asnumpy()) + 1)


def test_head_grad():
    x = mx.nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 3
    y.backward(mx.nd.array([10.0, 20.0]))
    assert_almost_equal(x.grad, np.array([30.0, 60.0]))


def test_grad_req_add_and_zero():
    x = mx.nd.array([1.0, 1.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = (x * x).sum()
        y.backward()
    assert_almost_equal(x.grad, np.array([6.0, 6.0]))
    x.zero_grad()
    assert_almost_equal(x.grad, np.array([0.0, 0.0]))


def test_write_overwrites():
    x = mx.nd.array([2.0])
    x.attach_grad()
    for _ in range(2):
        with autograd.record():
            y = (x * x).sum()
        y.backward()
    assert_almost_equal(x.grad, np.array([4.0]))


def test_multi_input_multi_use():
    a = mx.nd.array([3.0])
    b = mx.nd.array([4.0])
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        c = a * b + a  # dc/da = b + 1, dc/db = a
    c.backward()
    assert_almost_equal(a.grad, np.array([5.0]))
    assert_almost_equal(b.grad, np.array([3.0]))


def test_is_recording_training():
    assert not autograd.is_recording()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
        with autograd.pause():
            assert not autograd.is_recording()
        with autograd.predict_mode():
            assert not autograd.is_training()
    with autograd.train_mode():
        assert autograd.is_training()


def test_detach():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 3
        z = (y.detach() * x).sum()
    z.backward()
    assert_almost_equal(x.grad, np.array([6.0]))  # y treated as constant


def test_autograd_grad_api():
    x = mx.nd.array([2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x * x).sum()
    (g,) = autograd.grad([y], [x])
    assert_almost_equal(g, 3 * x.asnumpy() ** 2)
    # .grad buffer untouched by autograd.grad
    assert_almost_equal(x.grad, np.zeros(2))


def test_retain_graph():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward(retain_graph=True)
    assert_almost_equal(x.grad, np.array([4.0]))
    y.backward()
    assert_almost_equal(x.grad, np.array([4.0]))


def test_custom_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = mx.nd.sigmoid(x)
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            (y,) = self.saved_tensors
            return dy * y * (1 - y)

    f = Sigmoid()
    x = mx.nd.array([0.5, -0.5])
    x.attach_grad()
    with autograd.record():
        y = f(x)
        z = y.sum()
    z.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    assert_almost_equal(x.grad, s * (1 - s), rtol=1e-5, atol=1e-6)


def test_mark_variables():
    x = mx.nd.array([1.0, 2.0])
    g = mx.nd.zeros((2,))
    autograd.mark_variables([x], [g])
    with autograd.record():
        y = (x * 4).sum()
    y.backward()
    assert_almost_equal(x.grad, np.array([4.0, 4.0]))


@with_seed()
def test_numeric_gradient_matmul():
    a = np.random.uniform(-1, 1, (3, 4)).astype("float32")
    b = np.random.uniform(-1, 1, (4, 2)).astype("float32")
    check_numeric_gradient(lambda x, y: mx.nd.dot(x, y), [a, b])


@with_seed()
def test_numeric_gradient_elemwise():
    x = np.random.uniform(0.5, 2.0, (5, 5)).astype("float32")
    check_numeric_gradient(lambda a: mx.nd.log(a) * mx.nd.sqrt(a), [x])


def test_getitem_grad():
    x = mx.nd.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with autograd.record():
        y = (x[0] * 2).sum()
    y.backward()
    assert_almost_equal(x.grad, np.array([[2.0, 2.0], [0.0, 0.0]]))


def test_multi_output_op_grad():
    x = mx.nd.array([[1.0, 2.0, 3.0, 4.0]])
    x.attach_grad()
    with autograd.record():
        parts = mx.nd.split(x, 2, axis=1)
        z = (parts[0] * 2 + parts[1] * 3).sum()
    z.backward()
    assert_almost_equal(x.grad, np.array([[2.0, 2.0, 3.0, 3.0]]))


def test_stop_gradient_blocks():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        z = (3 * mx.nd.stop_gradient(x * x)).sum()
    z.backward()
    assert float(x.grad.asscalar()) == 0.0


def test_function_grad_alignment_with_constant_input():
    """Custom Function must pair grads positionally even when an earlier
    input is not attached (regression for provenance filtering bug)."""

    class F(autograd.Function):
        def forward(self, a, b):
            return a * b

        def backward(self, dy):
            return dy * 0 + 111, dy * 0 + 222

    c = mx.nd.array([1.0])
    v = mx.nd.array([1.0])
    v.attach_grad()
    with autograd.record():
        out = F()(c, v).sum()
    out.backward()
    assert float(v.grad.asscalar()) == 222.0


def test_grad_rejects_unmarked_intermediate():
    import pytest

    x = mx.nd.array([1.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        z = (y * 3).sum()
    with pytest.raises(ValueError):
        autograd.grad([z], [y])


def test_dropout_eval_identity_train_random():
    d = mx.nd.Dropout(mx.nd.ones((4, 4)), p=0.5)
    assert float(d.sum().asscalar()) == 16.0
    with autograd.record():
        d2 = mx.nd.Dropout(mx.nd.ones((200,)), p=0.5)
    # sum()!=200 is a bad oracle: it trips whenever exactly half the mask
    # survives (~5.6% of seeds).  Dropped-count > 0 fails with p = 2^-200.
    assert int((d2.asnumpy() == 0).sum()) > 0


def test_dropout_fast_path_unbiased(monkeypatch):
    """The uint8-bits fast path rescales by its own quantized keep-prob, so
    surviving values are exactly data/keep_q and the empirical drop rate
    tracks p to the 1/256 quantization."""
    monkeypatch.setenv("MXNET_TPU_FAST_DROPOUT", "1")
    mx.random.seed(7)
    n = 200_000
    with autograd.record():
        out = mx.nd.Dropout(mx.nd.ones((n,)), p=0.1).asnumpy()
    kept = out[out != 0]
    thresh = round(0.9 * 256)
    np.testing.assert_allclose(kept, 256.0 / thresh, rtol=1e-6)
    drop_rate = 1.0 - len(kept) / n
    assert abs(drop_rate - (1 - thresh / 256.0)) < 0.01


def test_second_order_grad_basic():
    """grad(create_graph=True): d/dx of ||grad sum(x^3)||^2 == 36 x^3
    (parity: tests/python/unittest/test_higher_order_grad.py idiom)."""
    x = mx.nd.array(np.array([1.0, 2.0, -0.5], np.float32))
    x.attach_grad()
    with autograd.record():
        y = (x ** 3).sum()
        g = autograd.grad(y, x, create_graph=True)
        z = (g * g).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 36 * x.asnumpy() ** 3,
                               rtol=1e-5)


def test_second_order_grad_matches_jax_oracle():
    """Gradient penalty d/dx and d/dw of ||∂f/∂x||² vs functional jax —
    the cross-term through the replayed forward must be exact."""
    import jax
    import jax.numpy as jnp

    xv = np.array([0.3, -1.2, 0.8], np.float32)
    wv = np.array([0.5, 2.0, -1.0], np.float32)

    def f(x, w):
        return jnp.sum(jnp.tanh(x * w))

    def pen(x, w):
        return jnp.sum(jax.grad(f, argnums=0)(x, w) ** 2)

    want_x = np.asarray(jax.grad(pen, argnums=0)(jnp.array(xv), jnp.array(wv)))
    want_w = np.asarray(jax.grad(pen, argnums=1)(jnp.array(xv), jnp.array(wv)))

    x = mx.nd.array(xv)
    w = mx.nd.array(wv)
    x.attach_grad()
    w.attach_grad()
    with autograd.record():
        y = mx.nd.tanh(x * w).sum()
        gx = autograd.grad(y, x, create_graph=True)
        p = (gx * gx).sum()
    p.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), want_x, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(w.grad.asnumpy(), want_w, rtol=1e-4, atol=1e-6)


def test_third_order_grad():
    """create_graph composes: d³/dx³ of x⁴ (summed) is 24x."""
    x = mx.nd.array(np.array([1.5, -2.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = (x ** 4).sum()
        g1 = autograd.grad(y, x, create_graph=True)   # 4x³
        g2 = autograd.grad(g1.sum(), x, create_graph=True)  # 12x²
        z3 = g2.sum()
    z3.backward()                                     # 24x
    np.testing.assert_allclose(x.grad.asnumpy(), 24 * x.asnumpy(), rtol=1e-5)


def test_second_order_grad_wrt_intermediate():
    """create_graph also returns grads w.r.t. intermediates (not only
    marked leaves)."""
    x = mx.nd.array(np.array([2.0], np.float32))
    x.attach_grad()
    with autograd.record():
        h = x * x           # intermediate
        y = (h * h).sum()   # x^4
        gh = autograd.grad(y, h, create_graph=True)  # 2h = 2x²
        z = (gh * gh).sum()                          # 4x⁴
    z.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 16 * x.asnumpy() ** 3,
                               rtol=1e-5)


def test_create_graph_immune_to_inplace_mutation():
    """Second-order replay uses record-time snapshots: mutating x after
    the forward must not corrupt the gradient."""
    x = mx.nd.array(np.array([3.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
        x[:] = 0.0  # in-place mutation after the recorded op
        g = autograd.grad(y, x, create_graph=True)
    np.testing.assert_allclose(g.asnumpy(), [6.0])  # 2*x at record time


def test_create_graph_with_numpy_calling_function():
    """A custom Function whose backward uses asnumpy() must not break an
    unrelated create_graph pass (it runs eagerly, grads are constants)."""

    class NumpyBackward(autograd.Function):
        def forward(self, a):
            return a * 2.0

        def backward(self, dy):
            scale = float(dy.sum().asnumpy())  # eager-only operation
            return dy * (2.0 if scale == scale else 0.0)

    w = mx.nd.array(np.array([1.0, 2.0], np.float32))
    w.attach_grad()
    a = mx.nd.array(np.array([0.5, 0.5], np.float32))
    a.attach_grad()
    with autograd.record():
        y = (w ** 2).sum() + NumpyBackward()(a).sum()
        g = autograd.grad(y, w, create_graph=True)
        z = (g * g).sum()
    z.backward()
    np.testing.assert_allclose(w.grad.asnumpy(), 8 * w.asnumpy(), rtol=1e-5)


def test_backward_frees_replay_state():
    """Plain first-order backward must release the replay snapshot along
    with the vjp residuals (peak-memory contract)."""
    x = mx.nd.array(np.array([2.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    node = y._prov[0]
    assert node._replay_raw is not None
    y.backward()
    assert node.vjp_fn is None
    assert node._replay_fn is None and node._replay_raw is None


@with_seed()
def test_leaf_survives_inplace_update():
    """`w -= lr * w.grad` outside record() — the reference's manual-SGD
    idiom — must keep the attach_grad leaf on the tape (round-4 fix:
    _inplace used to wipe the leaf provenance)."""
    w = mx.nd.array(np.array([4.0, -3.0], np.float32))
    w.attach_grad()
    losses = []
    for _ in range(25):
        with autograd.record():
            loss = (w * w).sum()
        loss.backward()
        w -= 0.1 * w.grad
        losses.append(float(loss.asnumpy()))
    assert losses[-1] < 1e-2 * losses[0], losses[-1]


def test_second_order_grad_through_rnn_megaop():
    """Gradient-penalty (||d loss/d data||²) through the fused RNN scan vs
    the functional jax oracle — create_graph must compose with lax.scan."""
    import jax
    import jax.numpy as jnp

    from incubator_mxnet_tpu.ops.rnn_ops import rnn_mega, rnn_param_size

    T, B, C, H = 3, 2, 2, 3
    rng = np.random.RandomState(0)
    xv = rng.uniform(-1, 1, (T, B, C)).astype(np.float32)
    pv = rng.uniform(-0.3, 0.3, (rnn_param_size("gru", C, H),)).astype(np.float32)

    def f(x):
        return jnp.sum(rnn_mega(x, jnp.asarray(pv), mode="gru", state_size=H))

    def pen(x):
        return jnp.sum(jax.grad(f)(x) ** 2)

    want = np.asarray(jax.grad(pen)(jnp.asarray(xv)))

    x = mx.nd.array(xv)
    x.attach_grad()
    with autograd.record():
        y = mx.nd.RNN(x, mx.nd.array(pv), mode="gru", state_size=H).sum()
        g = autograd.grad(y, x, create_graph=True)
        p = (g * g).sum()
    p.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), want, rtol=1e-3, atol=1e-5)


def test_second_order_grad_through_deformable_conv():
    import jax
    import jax.numpy as jnp

    from incubator_mxnet_tpu.ops.spatial import deformable_convolution

    rng = np.random.RandomState(1)
    xv = rng.randn(1, 2, 5, 5).astype(np.float32)
    wv = rng.randn(2, 2, 3, 3).astype(np.float32)
    off = np.full((1, 18, 5, 5), 0.37, np.float32)

    def f(x):
        return jnp.sum(deformable_convolution(
            x, jnp.asarray(off), jnp.asarray(wv), kernel=(3, 3), pad=(1, 1),
            num_filter=2, no_bias=True))

    def pen(x):
        return jnp.sum(jax.grad(f)(x) ** 2)

    want = np.asarray(jax.grad(pen)(jnp.asarray(xv)))

    x = mx.nd.array(xv)
    x.attach_grad()
    with autograd.record():
        y = mx.nd._contrib_DeformableConvolution(
            x, mx.nd.array(off), mx.nd.array(wv), kernel=(3, 3), pad=(1, 1),
            num_filter=2, no_bias=True).sum()
        g = autograd.grad(y, x, create_graph=True)
        p = (g * g).sum()
    p.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), want, rtol=1e-3, atol=1e-4)
