"""Serving tier (ISSUE 8): continuous batching under a latency SLO.

Covers the acceptance checklist: batch formation respects
``max_batch_size`` + deadline dispatch, bucket padding round-trips exact
results vs unbatched ``Predictor.forward``, the bf16 AMP tier stays
within tolerance, the SLO-violation counter fires exactly once per late
request, concurrent submitters get their own results back, and
``close()`` drains the queue.
"""
import threading
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
import incubator_mxnet_tpu.symbol as S
from incubator_mxnet_tpu import profiler
from incubator_mxnet_tpu.predictor import Predictor
from incubator_mxnet_tpu.serving import InferenceServer, ShapeBucketer

FEAT = 4
HID = 6


def _model(seed=0):
    """Padding-safe per-position model: FC(flatten=False) + tanh over
    (batch, length, FEAT) — parameter shapes are length-independent."""
    S.symbol._reset_naming()
    data = S.var("data")
    fc = S.FullyConnected(data, num_hidden=HID, flatten=False, name="fc1")
    sym = S.Activation(fc, act_type="tanh", name="t1")
    rng = np.random.RandomState(seed)
    params = {
        "arg:fc1_weight": mx.nd.array(rng.randn(HID, FEAT).astype(np.float32)),
        "arg:fc1_bias": mx.nd.array(rng.randn(HID).astype(np.float32)),
    }
    return sym, params


def _server(sym, params, **kw):
    kw.setdefault("max_batch_size", 4)
    kw.setdefault("max_queue_ms", 50.0)
    kw.setdefault("max_length", 16)
    kw.setdefault("name", "serving_test")
    return InferenceServer(sym, params, {"data": (None, FEAT)}, **kw)


class TestShapeBucketer:
    def test_power_of_two_ladder(self):
        b = ShapeBucketer(max_length=100, min_bucket=8)
        assert b.buckets == (8, 16, 32, 64, 100)

    def test_explicit_buckets(self):
        b = ShapeBucketer(buckets=[64, 16, 32])
        assert b.buckets == (16, 32, 64)

    def test_bucket_for_boundaries(self):
        b = ShapeBucketer(buckets=[8, 16])
        assert b.bucket_for(1) == 8
        assert b.bucket_for(8) == 8
        assert b.bucket_for(9) == 16
        assert b.bucket_for(16) == 16

    def test_too_long_raises(self):
        b = ShapeBucketer(buckets=[8])
        with pytest.raises(ValueError):
            b.bucket_for(9)

    def test_needs_max_length(self):
        with pytest.raises(ValueError):
            ShapeBucketer()


class TestBatchFormation:
    def test_full_batches_respect_max_batch_size(self):
        sym, params = _model()
        srv = _server(sym, params, max_batch_size=4, max_queue_ms=5000.0)
        try:
            rng = np.random.RandomState(1)
            pendings = [srv.submit({"data": rng.rand(8, FEAT)
                                    .astype(np.float32)})
                        for _ in range(8)]
            outs = [p.result(timeout=30.0) for p in pendings]
            assert all(o.shape == (8, HID) for o in outs)
            st = srv.stats()
            assert st["batches"] == 2            # two full batches of 4
            assert st["batch_requests"] == 8
            # full batches dispatched immediately — nobody waited out the
            # 5 s queueing deadline
            assert max(p.latency_ms for p in pendings) < 2000.0
        finally:
            srv.close()

    def test_deadline_dispatches_partial_batch(self):
        sym, params = _model()
        srv = _server(sym, params, max_batch_size=8, max_queue_ms=100.0)
        try:
            t0 = time.perf_counter()
            out = srv.infer({"data": np.ones((3, FEAT), np.float32)},
                            timeout=30.0)
            elapsed = time.perf_counter() - t0
            assert out.shape == (3, HID)
            # a lone request cannot fill the batch: it must go out on its
            # deadline, not hang until more traffic shows up
            assert 0.05 <= elapsed < 10.0
            assert srv.stats()["batches"] == 1
        finally:
            srv.close()

    def test_mixed_lengths_split_into_bucket_groups(self):
        sym, params = _model()
        srv = _server(sym, params, max_batch_size=4, max_queue_ms=100.0,
                      length_buckets=[8, 16])
        try:
            rng = np.random.RandomState(2)
            pendings = [srv.submit({"data": rng.rand(L, FEAT)
                                    .astype(np.float32)})
                        for L in (3, 12, 5, 16)]
            for p in pendings:
                p.result(timeout=30.0)
            st = srv.stats()
            # one batch per length bucket: (3,5)->8 and (12,16)->16
            assert st["batches"] == 2
            assert st["batch_requests"] == 4
        finally:
            srv.close()

    def test_past_deadline_head_beats_full_batches(self):
        """A sustained flood of full batches in one length bucket must not
        starve a past-deadline request in another bucket: the deadline
        check outranks the full-batch preference."""
        sym, params = _model()
        srv = _server(sym, params, max_batch_size=2, max_queue_ms=50.0,
                      length_buckets=[8, 16])
        try:
            rng = np.random.RandomState(8)
            minority = srv.submit({"data": rng.rand(12, FEAT)
                                   .astype(np.float32)})
            stop = threading.Event()

            def flood():  # keeps bucket-8 full batches always available
                while not stop.is_set():
                    ps = [srv.submit({"data": rng.rand(4, FEAT)
                                      .astype(np.float32)})
                          for _ in range(4)]
                    for p in ps:
                        p.result(timeout=30.0)

            th = threading.Thread(target=flood, daemon=True)
            th.start()
            try:
                out = minority.result(timeout=10.0)
            finally:
                stop.set()
                th.join(30.0)
            assert out.shape == (12, HID)
        finally:
            srv.close()

    def test_submit_validates_inputs(self):
        sym, params = _model()
        srv = _server(sym, params, max_length=16)
        try:
            with pytest.raises(ValueError):   # too long for the top bucket
                srv.submit({"data": np.ones((17, FEAT), np.float32)})
            with pytest.raises(ValueError):   # wrong fixed dim
                srv.submit({"data": np.ones((4, FEAT + 1), np.float32)})
            with pytest.raises(ValueError):   # wrong input name
                srv.submit({"nope": np.ones((4, FEAT), np.float32)})
        finally:
            srv.close()


def _model2(seed=0):
    """Two-output padding-safe model: Group([tanh(fc), fc]) — both
    per-position (batch, length, HID)."""
    S.symbol._reset_naming()
    data = S.var("data")
    fc = S.FullyConnected(data, num_hidden=HID, flatten=False, name="fc1")
    t = S.Activation(fc, act_type="tanh", name="t1")
    rng = np.random.RandomState(seed)
    w = rng.randn(HID, FEAT).astype(np.float32)
    b = rng.randn(HID).astype(np.float32)
    params = {"arg:fc1_weight": mx.nd.array(w), "arg:fc1_bias": mx.nd.array(b)}
    return S.Group([t, fc]), params, w, b


class TestMultiOutput:
    def test_list_result_and_per_output_unpad(self):
        sym, params, w, b = _model2()
        srv = InferenceServer(sym, params, {"data": (None, FEAT)},
                              max_batch_size=4, max_queue_ms=50.0,
                              max_length=16, unpad_output_axis=[0, 0],
                              name="mo_test")
        try:
            rng = np.random.RandomState(1)
            x = rng.rand(5, FEAT).astype(np.float32)
            out = srv.infer({"data": x}, timeout=30.0)
            assert isinstance(out, list) and len(out) == 2
            assert out[0].shape == (5, HID) and out[1].shape == (5, HID)
            ref = x @ w.T + b
            np.testing.assert_allclose(out[1], ref, rtol=1e-5, atol=1e-6)
            np.testing.assert_allclose(out[0], np.tanh(ref),
                                       rtol=1e-5, atol=1e-6)
        finally:
            srv.close()

    def test_auto_unpads_every_output(self):
        sym, params, _, _ = _model2()
        srv = InferenceServer(sym, params, {"data": (None, FEAT)},
                              max_batch_size=4, max_queue_ms=50.0,
                              max_length=16, name="mo_auto")   # auto spec
        try:
            out = srv.infer({"data": np.ones((3, FEAT), np.float32)},
                            timeout=30.0)
            assert [o.shape for o in out] == [(3, HID), (3, HID)]
        finally:
            srv.close()

    def test_dict_spec_leaves_unlisted_outputs_padded(self):
        sym, params, _, _ = _model2()
        srv = InferenceServer(sym, params, {"data": (None, FEAT)},
                              max_batch_size=4, max_queue_ms=50.0,
                              length_buckets=[8], unpad_output_axis={0: 0},
                              name="mo_dict")
        try:
            out = srv.infer({"data": np.ones((5, FEAT), np.float32)},
                            timeout=30.0)
            assert out[0].shape == (5, HID)     # unpadded
            assert out[1].shape == (8, HID)     # bucket-padded, untouched
        finally:
            srv.close()

    def test_wrong_spec_length_fails_at_construction(self):
        sym, params, _, _ = _model2()
        with pytest.raises(ValueError, match="3 entries.*2 outputs"):
            InferenceServer(sym, params, {"data": (None, FEAT)},
                            max_batch_size=4, max_queue_ms=20.0,
                            max_length=16, unpad_output_axis=[0, 0, 0],
                            name="mo_bad", warmup=False, autostart=False)

    def test_single_output_keeps_bare_array_contract(self):
        sym, params = _model()
        srv = _server(sym, params)
        try:
            out = srv.infer({"data": np.ones((4, FEAT), np.float32)},
                            timeout=30.0)
            assert isinstance(out, np.ndarray)   # not a 1-element list
        finally:
            srv.close()


class TestExactness:
    def _reference(self, sym, params, sample, bucket):
        pred = Predictor(sym, params, {"data": (1, bucket, FEAT)})
        buf = np.zeros((1, bucket, FEAT), np.float32)
        buf[0, :sample.shape[0]] = sample
        return pred.predict(data=buf)[0, :sample.shape[0]]

    def test_padding_roundtrip_exact(self):
        sym, params = _model()
        srv = _server(sym, params, max_queue_ms=5.0, length_buckets=[8, 16])
        try:
            rng = np.random.RandomState(3)
            for L in (2, 8, 11, 16):
                x = rng.rand(L, FEAT).astype(np.float32)
                out = srv.infer({"data": x}, timeout=30.0)
                ref = self._reference(sym, params, x,
                                      srv._len_bucketer.bucket_for(L))
                assert out.shape == ref.shape
                np.testing.assert_array_equal(out, ref)
        finally:
            srv.close()

    def test_batched_rows_match_unbatched(self):
        sym, params = _model()
        srv = _server(sym, params, max_batch_size=4, max_queue_ms=2000.0)
        try:
            rng = np.random.RandomState(4)
            xs = [rng.rand(5, FEAT).astype(np.float32) for _ in range(4)]
            pendings = [srv.submit({"data": x}) for x in xs]
            for x, p in zip(xs, pendings):
                ref = self._reference(sym, params, x, 8)
                np.testing.assert_allclose(p.result(timeout=30.0), ref,
                                           rtol=0, atol=1e-6)
        finally:
            srv.close()

    def test_bf16_tier_within_tolerance(self):
        sym, params = _model()
        srv32 = _server(sym, params, max_queue_ms=5.0, name="srv_fp32")
        srv16 = _server(sym, params, max_queue_ms=5.0, name="srv_bf16",
                        amp_dtype="bfloat16")
        try:
            rng = np.random.RandomState(5)
            x = rng.rand(7, FEAT).astype(np.float32)
            o32 = srv32.infer({"data": x}, timeout=30.0)
            o16 = srv16.infer({"data": x}, timeout=30.0)
            assert str(o16.dtype) == "bfloat16"
            np.testing.assert_allclose(o32, o16.astype(np.float32),
                                       rtol=0, atol=0.05)
        finally:
            srv32.close()
            srv16.close()


class TestObservability:
    def test_slo_violation_exactly_once_per_late_request(self):
        sym, params = _model()
        # an SLO nothing can meet: every request is late exactly once
        srv = _server(sym, params, max_queue_ms=5.0, slo_ms=1e-6)
        try:
            before = profiler.counters()["serving_slo_violation"]
            n = 6
            pendings = [srv.submit({"data": np.ones((4, FEAT), np.float32)})
                        for _ in range(n)]
            for p in pendings:
                p.result(timeout=30.0)
            after = profiler.counters()["serving_slo_violation"]
            assert after - before == n
            assert srv.stats()["slo_violations"] == n
        finally:
            srv.close()

    def test_no_violation_under_generous_slo(self):
        sym, params = _model()
        srv = _server(sym, params, max_queue_ms=5.0, slo_ms=60_000.0)
        try:
            before = profiler.counters()["serving_slo_violation"]
            srv.infer({"data": np.ones((4, FEAT), np.float32)}, timeout=30.0)
            assert profiler.counters()["serving_slo_violation"] == before
        finally:
            srv.close()

    def test_bucket_hits_after_warmup(self):
        sym, params = _model()
        srv = _server(sym, params, max_queue_ms=5.0)
        try:
            for _ in range(3):
                srv.infer({"data": np.ones((4, FEAT), np.float32)},
                          timeout=30.0)
            st = srv.stats()
            assert st["bucket_misses"] == 0
            assert st["bucket_miss_after_warmup"] == 0
            assert st["bucket_hits"] == 3
        finally:
            srv.close()

    def test_metrics_provider_in_snapshot_and_prometheus(self):
        sym, params = _model()
        srv = _server(sym, params, max_queue_ms=5.0, name="srv_metrics")
        try:
            srv.infer({"data": np.ones((2, FEAT), np.float32)}, timeout=30.0)
            snap = profiler.metrics_snapshot()
            prov = snap["providers"]["srv_metrics"]
            assert prov["completed"] >= 1
            assert prov["latency_ms_p99"] is not None
            text = profiler.render_prometheus()
            assert "mxnet_srv_metrics_latency_ms_p99" in text
            assert "mxnet_srv_metrics_queue_depth" in text
        finally:
            srv.close()
        # a closed server leaves the scrape surface
        assert "srv_metrics" not in profiler.metrics_snapshot()["providers"]

    def test_spans_recorded(self, tmp_path):
        sym, params = _model()
        srv = _server(sym, params, max_queue_ms=5.0)
        try:
            profiler.set_config(filename=str(tmp_path / "trace.json"))
            profiler.start()
            srv.infer({"data": np.ones((2, FEAT), np.float32)}, timeout=30.0)
            profiler.stop()
            import json

            path = profiler.dump()
            with open(path) as f:
                trace = json.load(f)
            names = {e.get("name") for e in trace["traceEvents"]}
            for want in ("serving.enqueue", "serving.batch_form",
                         "serving.dispatch", "serving.complete"):
                assert want in names, f"missing span {want}"
        finally:
            srv.close()


class TestConcurrency:
    def test_concurrent_submitters_get_their_own_results(self):
        sym, params = _model()
        srv = _server(sym, params, max_batch_size=4, max_queue_ms=20.0,
                      length_buckets=[8, 16])
        try:
            lengths = {0: 3, 1: 8, 2: 11, 3: 16, 4: 5, 5: 13}
            expected = {}
            ref = TestExactness()
            for tid, L in lengths.items():
                x = np.full((L, FEAT), tid + 1, np.float32) / 10.0
                expected[tid] = ref._reference(
                    sym, params, x, srv._len_bucketer.bucket_for(L))
            errors = []

            def worker(tid):
                L = lengths[tid]
                x = np.full((L, FEAT), tid + 1, np.float32) / 10.0
                for _ in range(4):
                    out = srv.infer({"data": x}, timeout=30.0)
                    if out.shape != expected[tid].shape or \
                            not np.allclose(out, expected[tid], atol=1e-6):
                        errors.append(tid)

            threads = [threading.Thread(target=worker, args=(t,))
                       for t in lengths]
            for t in threads:
                t.start()
            for t in threads:
                t.join(60.0)
            assert not errors, f"cross-request leakage for threads {errors}"
            assert srv.stats()["completed"] == 4 * len(lengths)
        finally:
            srv.close()


class TestLifecycle:
    def test_close_drains_queue(self):
        sym, params = _model()
        # deadline far away and batch never fills: only close() can flush
        srv = _server(sym, params, max_batch_size=8, max_queue_ms=60_000.0)
        rng = np.random.RandomState(6)
        pendings = [srv.submit({"data": rng.rand(4, FEAT)
                                .astype(np.float32)})
                    for _ in range(5)]
        srv.close()
        for p in pendings:
            assert p.done()
            assert p.result(timeout=1.0).shape == (4, HID)
        assert srv.stats()["completed"] == 5

    def test_close_without_drain_fails_pending(self):
        sym, params = _model()
        srv = _server(sym, params, max_batch_size=8, max_queue_ms=60_000.0)
        p = srv.submit({"data": np.ones((4, FEAT), np.float32)})
        srv.close(drain=False)
        # either the scheduler grabbed it before close, or it was failed;
        # both are terminal — never a hang
        try:
            p.result(timeout=5.0)
        except RuntimeError as e:
            assert "closed" in str(e)

    def test_submit_after_close_raises(self):
        sym, params = _model()
        srv = _server(sym, params)
        srv.close()
        with pytest.raises(RuntimeError):
            srv.submit({"data": np.ones((4, FEAT), np.float32)})

    def test_context_manager(self):
        sym, params = _model()
        with _server(sym, params, max_queue_ms=5.0) as srv:
            out = srv.infer({"data": np.ones((2, FEAT), np.float32)},
                            timeout=30.0)
            assert out.shape == (2, HID)


class TestFixedShapeInputs:
    def test_no_variable_axis(self):
        sym, params = _model()
        srv = InferenceServer(sym, params, {"data": (3, FEAT)},
                              max_batch_size=2, max_queue_ms=20.0,
                              name="srv_fixed")
        try:
            x = np.random.RandomState(7).rand(3, FEAT).astype(np.float32)
            out = srv.infer({"data": x}, timeout=30.0)
            pred = Predictor(sym, params, {"data": (1, 3, FEAT)})
            np.testing.assert_allclose(out, pred.predict(data=x[None])[0],
                                       rtol=0, atol=1e-6)
        finally:
            srv.close()


class TestBenchSmoke:
    @pytest.mark.slow
    def test_harness_smoke(self):
        import benchmark.opperf.serving as bench

        line = bench.run(n_requests=40, layers=1, feat=8, max_length=32,
                         max_batch=4, slo_ms=100.0, smoke=True)
        assert line["served"] is not None
        assert not line["recompiles_after_warmup"]["served"]
        assert line["recompiles_after_warmup"]["bucket_miss_after_warmup"] == 0
