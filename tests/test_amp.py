"""AMP tests (parity idioms: tests/python/gpu/test_amp.py — list casting,
loss scaler dynamics, trainer integration, converted-model correctness)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import amp, gluon
from incubator_mxnet_tpu.gluon import nn


@pytest.fixture(autouse=True)
def _amp_off():
    yield
    amp.disable()


def _net(seed=3):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    net(mx.nd.zeros((2, 8)))
    return net


class TestAmpCasting:
    def test_target_op_runs_bf16(self):
        amp.init("bfloat16")
        x = mx.nd.ones((4, 8))
        w = mx.nd.ones((16, 8))
        out = mx.nd.FullyConnected(x, w, None, num_hidden=16, no_bias=True)
        assert out.dtype == np.dtype("bfloat16")

    def test_fp32_op_casts_up(self):
        amp.init("bfloat16")
        x = mx.nd.ones((4, 8), dtype="bfloat16")
        out = mx.nd.exp(x)
        assert out.dtype == np.float32

    def test_softmax_stays_bf16_with_fp32_internals(self):
        # softmax/LayerNorm left the FP32 list in round 3: the op computes
        # exp/stats in fp32 internally and returns the input dtype, so the
        # bf16 activation stream has no hook cast copies around it.
        amp.init("bfloat16")
        x = mx.nd.ones((4, 8), dtype="bfloat16")
        assert mx.nd.softmax(x).dtype == np.dtype("bfloat16")

    def test_widest_op_promotes(self):
        amp.init("bfloat16")
        a = mx.nd.ones((4,), dtype="bfloat16")
        b = mx.nd.ones((4,), dtype="float32")
        out = mx.nd.broadcast_add(a, b)
        assert out.dtype == np.float32

    def test_disabled_is_nop(self):
        x = mx.nd.ones((4, 8))
        w = mx.nd.ones((16, 8))
        out = mx.nd.FullyConnected(x, w, None, num_hidden=16, no_bias=True)
        assert out.dtype == np.float32

    def test_gluon_forward_close_to_fp32(self):
        net = _net()
        x = mx.nd.array(np.random.RandomState(0).randn(8, 8).astype(np.float32))
        ref = net(x).asnumpy()
        amp.init("bfloat16")
        out = net(x).asnumpy()
        np.testing.assert_allclose(out, ref, rtol=2e-2, atol=2e-2)


class TestLossScaler:
    def test_dynamics(self):
        s = amp.LossScaler(init_scale=8.0, scale_factor=2.0, scale_window=2)
        s.update_scale(False)
        s.update_scale(False)
        assert s.loss_scale == 16.0  # doubled after window good steps
        s.update_scale(True)
        assert s.loss_scale == 8.0  # halved on overflow

    def test_trainer_skips_on_overflow(self):
        net = _net()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1})
        amp.init_trainer(trainer)
        x = mx.nd.array(np.random.RandomState(0).randn(4, 8).astype(np.float32))
        y = mx.nd.array(np.array([0., 1., 2., 3.], np.float32))
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        w0 = list(net.collect_params().values())[0].data().asnumpy().copy()

        # poison one grad with inf → step must be skipped
        with mx.autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        p0 = [p for p in trainer._params if p.grad_req != "null"][0]
        g = p0.grad()
        import jax.numpy as jnp
        g._data = g._data.at[0].set(jnp.inf)
        scale_before = trainer._amp_loss_scaler.loss_scale
        trainer.step(4)
        np.testing.assert_array_equal(
            w0, list(net.collect_params().values())[0].data().asnumpy())
        assert trainer._amp_loss_scaler.loss_scale < scale_before

    def test_scale_loss_roundtrip_training(self):
        """fp16-style scaled training must converge like unscaled."""
        net_a, net_b = _net(seed=9), _net(seed=9)
        rng = np.random.RandomState(1)
        X = rng.randn(32, 8).astype(np.float32)
        Y = rng.randint(0, 4, (32,)).astype(np.float32)
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

        tr_a = gluon.Trainer(net_a.collect_params(), "sgd", {"learning_rate": 0.1})
        for _ in range(3):
            with mx.autograd.record():
                la = loss_fn(net_a(mx.nd.array(X)), mx.nd.array(Y))
            la.backward()
            tr_a.step(32)

        tr_b = gluon.Trainer(net_b.collect_params(), "sgd", {"learning_rate": 0.1})
        amp.init_trainer(tr_b)
        for _ in range(3):
            with mx.autograd.record():
                lb = loss_fn(net_b(mx.nd.array(X)), mx.nd.array(Y))
                with amp.scale_loss(lb, tr_b) as scaled:
                    pass
            scaled.backward()
            tr_b.step(32)

        pa = net_a._collect_params_with_prefix()
        pb = net_b._collect_params_with_prefix()
        for k in pa:
            np.testing.assert_allclose(pa[k].data().asnumpy(),
                                       pb[k].data().asnumpy(),
                                       rtol=1e-4, atol=1e-5, err_msg=k)


class TestConvertHybridBlock:
    def test_params_cast_and_forward_runs(self):
        net = _net()
        amp.convert_hybrid_block(net, "bfloat16")
        for p in net.collect_params().values():
            assert p.data().dtype == np.dtype("bfloat16")
        out = net(mx.nd.ones((2, 8), dtype="bfloat16"))
        assert out.shape == (2, 4)


class TestMixedDtypeTape:
    def test_hybridized_amp_backward(self):
        """fp32 loss head over a bf16 hybridized block: the tape must cast
        cotangents at node boundaries (regression: vjp dtype mismatch)."""
        net = _net()
        net.hybridize()
        amp.init("bfloat16")
        x = mx.nd.array(np.random.RandomState(0).randn(8, 8).astype(np.float32))
        y = mx.nd.array(np.arange(8, dtype=np.float32) % 4)
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        with mx.autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        for p in net.collect_params().values():
            g = p.grad().asnumpy()
            assert np.isfinite(g).all()
            assert g.dtype == np.float32  # master-grad stays fp32


class TestReviewRegressions:
    def test_unscale_then_step_single_divide(self):
        """amp.unscale() before step must not divide by the scale twice."""
        net_a, net_b = _net(seed=4), _net(seed=4)
        rng = np.random.RandomState(2)
        X = rng.randn(16, 8).astype(np.float32)
        Y = rng.randint(0, 4, (16,)).astype(np.float32)
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

        tr_a = gluon.Trainer(net_a.collect_params(), "sgd", {"learning_rate": 0.1})
        with mx.autograd.record():
            la = loss_fn(net_a(mx.nd.array(X)), mx.nd.array(Y))
        la.backward()
        tr_a.step(16)

        tr_b = gluon.Trainer(net_b.collect_params(), "sgd", {"learning_rate": 0.1})
        amp.init_trainer(tr_b)
        with mx.autograd.record():
            lb = loss_fn(net_b(mx.nd.array(X)), mx.nd.array(Y))
            with amp.scale_loss(lb, tr_b) as scaled:
                pass
        scaled.backward()
        amp.unscale(tr_b)  # clipping-style flow
        tr_b.step(16)

        pa = net_a._collect_params_with_prefix()
        pb = net_b._collect_params_with_prefix()
        for k in pa:
            np.testing.assert_allclose(pa[k].data().asnumpy(),
                                       pb[k].data().asnumpy(),
                                       rtol=1e-4, atol=1e-5, err_msg=k)

    def test_amp_init_invalidates_spmd_step_cache(self):
        from incubator_mxnet_tpu.parallel import SPMDTrainer, make_mesh
        net = _net(seed=6)
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        tr = SPMDTrainer(net, loss_fn, "sgd", {"learning_rate": 0.1},
                         mesh=make_mesh())
        X = mx.nd.array(np.random.RandomState(0).randn(8, 8).astype(np.float32))
        Y = mx.nd.array((np.arange(8) % 4).astype(np.float32))
        tr.step(X, Y)
        assert tr._step_cache
        amp.init("bfloat16")
        assert not tr._step_cache  # must retrace under the AMP hook

    def test_contrib_amp_path(self):
        assert mx.contrib.amp is mx.amp

    def test_convert_rebuilds_grad_buffer(self):
        net = _net()
        amp.convert_hybrid_block(net, "bfloat16")
        p = list(net.collect_params().values())[0]
        assert p.data().dtype == np.dtype("bfloat16")
        assert p.grad().dtype == np.dtype("bfloat16")

    def test_init_trainer_idempotent(self):
        net = _net(seed=8)
        tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
        amp.init_trainer(tr)
        step1 = tr.step
        amp.init_trainer(tr)  # must not stack a second wrapper
        assert tr.step is step1


def test_scoped_disable():
    amp.init("bfloat16")
    try:
        x = mx.nd.ones((2, 4))
        w = mx.nd.ones((3, 4))
        assert mx.nd.FullyConnected(x, w, None, num_hidden=3, no_bias=True).dtype == np.dtype("bfloat16")
        with amp.disabled():
            out = mx.nd.FullyConnected(x, w, None, num_hidden=3, no_bias=True)
            assert out.dtype == np.float32
        assert mx.nd.FullyConnected(x, w, None, num_hidden=3, no_bias=True).dtype == np.dtype("bfloat16")
    finally:
        amp.disable()


def test_convert_symbol_and_model_offline():
    """amp.convert_symbol/convert_model (round-5: the offline
    low_precision_pass analog over the new amp_cast ops): casts inserted
    around TARGET/FP32 ops, deferred shape inference flows through the
    wrappers, numerics within bf16 tolerance, FP32-op params stay fp32."""
    import incubator_mxnet_tpu.symbol as S

    S.symbol._reset_naming()
    data = S.var("data")
    c = S.Convolution(data, kernel=(3, 3), num_filter=4, pad=(1, 1), name="c1")
    r = S.Activation(c, act_type="relu", name="r1")
    f = S.FullyConnected(S.Flatten(r), num_hidden=3, name="fc1")
    net = S.SoftmaxOutput(f, S.var("softmax_label"), name="sm")

    rng = np.random.RandomState(0)
    x = rng.rand(2, 3, 8, 8).astype(np.float32)
    args = {"c1_weight": mx.nd.array(rng.randn(4, 3, 3, 3).astype(np.float32) * 0.1),
            "c1_bias": mx.nd.zeros(4),
            "fc1_weight": mx.nd.array(rng.randn(3, 256).astype(np.float32) * 0.1),
            "fc1_bias": mx.nd.zeros(3)}

    def fwd(sym, params):
        exe = sym.simple_bind(data=x.shape)
        exe.arg_dict["data"][:] = x
        for k, v in params.items():
            exe.arg_dict[k][:] = v.asnumpy()
        return exe.forward(is_train=False)[0].asnumpy()

    ref = fwd(net, args)
    csym, carg, caux = amp.convert_model(net, args, {},
                                         target_dtype="bfloat16")
    ops = [n.op for n in csym._topo() if n.op]
    assert ops.count("amp_cast") >= 4  # conv + fc inputs, softmax fp32 ins
    assert carg["fc1_weight"].dtype == np.dtype("bfloat16")
    out = fwd(csym, carg)
    assert np.abs(out - ref).max() < 0.05
    assert sorted(amp.list_lp16_ops())  # accessors exist and are non-empty
    assert "SoftmaxOutput" in amp.list_fp32_ops()
    # exclusion honors names — in the graph AND the param cast set
    csym2 = amp.convert_symbol(net, excluded_sym_names=("c1", "fc1", "sm"))
    assert [n.op for n in csym2._topo()].count("amp_cast") == 0
    _, carg3, _ = amp.convert_model(net, args, {},
                                    excluded_sym_names=("fc1",))
    assert carg3["fc1_weight"].dtype == np.float32
    assert carg3["c1_weight"].dtype == np.dtype("bfloat16")
    # checkpoint contract: tojson strips amp_cast by default
    import json as _json
    assert sum(1 for n in _json.loads(csym.tojson())["nodes"]
               if n["op"] == "amp_cast") == 0
    assert sum(1 for n in _json.loads(csym.tojson(remove_amp_cast=False))
               ["nodes"] if n["op"] == "amp_cast") > 0
