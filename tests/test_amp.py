"""AMP tests (parity idioms: tests/python/gpu/test_amp.py — list casting,
loss scaler dynamics, trainer integration, converted-model correctness)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import amp, gluon
from incubator_mxnet_tpu.gluon import nn


@pytest.fixture(autouse=True)
def _amp_off():
    yield
    amp.disable()


def _net(seed=3):
    mx.random.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    net(mx.nd.zeros((2, 8)))
    return net


class TestAmpCasting:
    def test_target_op_runs_bf16(self):
        amp.init("bfloat16")
        x = mx.nd.ones((4, 8))
        w = mx.nd.ones((16, 8))
        out = mx.nd.FullyConnected(x, w, None, num_hidden=16, no_bias=True)
        assert out.dtype == np.dtype("bfloat16")

    def test_fp32_op_casts_up(self):
        amp.init("bfloat16")
        x = mx.nd.ones((4, 8), dtype="bfloat16")
        out = mx.nd.exp(x)
        assert out.dtype == np.float32

    def test_softmax_stays_bf16_with_fp32_internals(self):
        # softmax/LayerNorm left the FP32 list in round 3: the op computes
        # exp/stats in fp32 internally and returns the input dtype, so the
        # bf16 activation stream has no hook cast copies around it.
        amp.init("bfloat16")
        x = mx.nd.ones((4, 8), dtype="bfloat16")
        assert mx.nd.softmax(x).dtype == np.dtype("bfloat16")

    def test_widest_op_promotes(self):
        amp.init("bfloat16")
        a = mx.nd.ones((4,), dtype="bfloat16")
        b = mx.nd.ones((4,), dtype="float32")
        out = mx.nd.broadcast_add(a, b)
        assert out.dtype == np.float32

    def test_disabled_is_nop(self):
        x = mx.nd.ones((4, 8))
        w = mx.nd.ones((16, 8))
        out = mx.nd.FullyConnected(x, w, None, num_hidden=16, no_bias=True)
        assert out.dtype == np.float32

    def test_gluon_forward_close_to_fp32(self):
        net = _net()
        x = mx.nd.array(np.random.RandomState(0).randn(8, 8).astype(np.float32))
        ref = net(x).asnumpy()
        amp.init("bfloat16")
        out = net(x).asnumpy()
        np.testing.assert_allclose(out, ref, rtol=2e-2, atol=2e-2)


class TestLossScaler:
    def test_dynamics(self):
        s = amp.LossScaler(init_scale=8.0, scale_factor=2.0, scale_window=2)
        s.update_scale(False)
        s.update_scale(False)
        assert s.loss_scale == 16.0  # doubled after window good steps
        s.update_scale(True)
        assert s.loss_scale == 8.0  # halved on overflow

    def test_trainer_skips_on_overflow(self):
        net = _net()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1})
        amp.init_trainer(trainer)
        x = mx.nd.array(np.random.RandomState(0).randn(4, 8).astype(np.float32))
        y = mx.nd.array(np.array([0., 1., 2., 3.], np.float32))
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        w0 = list(net.collect_params().values())[0].data().asnumpy().copy()

        # poison one grad with inf → step must be skipped
        with mx.autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        p0 = [p for p in trainer._params if p.grad_req != "null"][0]
        g = p0.grad()
        import jax.numpy as jnp
        g._data = g._data.at[0].set(jnp.inf)
        scale_before = trainer._amp_loss_scaler.loss_scale
        trainer.step(4)
        np.testing.assert_array_equal(
            w0, list(net.collect_params().values())[0].data().asnumpy())
        assert trainer._amp_loss_scaler.loss_scale < scale_before

    def test_scale_loss_roundtrip_training(self):
        """fp16-style scaled training must converge like unscaled."""
        net_a, net_b = _net(seed=9), _net(seed=9)
        rng = np.random.RandomState(1)
        X = rng.randn(32, 8).astype(np.float32)
        Y = rng.randint(0, 4, (32,)).astype(np.float32)
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

        tr_a = gluon.Trainer(net_a.collect_params(), "sgd", {"learning_rate": 0.1})
        for _ in range(3):
            with mx.autograd.record():
                la = loss_fn(net_a(mx.nd.array(X)), mx.nd.array(Y))
            la.backward()
            tr_a.step(32)

        tr_b = gluon.Trainer(net_b.collect_params(), "sgd", {"learning_rate": 0.1})
        amp.init_trainer(tr_b)
        for _ in range(3):
            with mx.autograd.record():
                lb = loss_fn(net_b(mx.nd.array(X)), mx.nd.array(Y))
                with amp.scale_loss(lb, tr_b) as scaled:
                    pass
            scaled.backward()
            tr_b.step(32)

        pa = net_a._collect_params_with_prefix()
        pb = net_b._collect_params_with_prefix()
        for k in pa:
            np.testing.assert_allclose(pa[k].data().asnumpy(),
                                       pb[k].data().asnumpy(),
                                       rtol=1e-4, atol=1e-5, err_msg=k)


class TestConvertHybridBlock:
    def test_params_cast_and_forward_runs(self):
        net = _net()
        amp.convert_hybrid_block(net, "bfloat16")
        for p in net.collect_params().values():
            assert p.data().dtype == np.dtype("bfloat16")
        out = net(mx.nd.ones((2, 8), dtype="bfloat16"))
        assert out.shape == (2, 4)


class TestMixedDtypeTape:
    def test_hybridized_amp_backward(self):
        """fp32 loss head over a bf16 hybridized block: the tape must cast
        cotangents at node boundaries (regression: vjp dtype mismatch)."""
        net = _net()
        net.hybridize()
        amp.init("bfloat16")
        x = mx.nd.array(np.random.RandomState(0).randn(8, 8).astype(np.float32))
        y = mx.nd.array(np.arange(8, dtype=np.float32) % 4)
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        with mx.autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        for p in net.collect_params().values():
            g = p.grad().asnumpy()
            assert np.isfinite(g).all()
            assert g.dtype == np.float32  # master-grad stays fp32


class TestReviewRegressions:
    def test_unscale_then_step_single_divide(self):
        """amp.unscale() before step must not divide by the scale twice."""
        net_a, net_b = _net(seed=4), _net(seed=4)
        rng = np.random.RandomState(2)
        X = rng.randn(16, 8).astype(np.float32)
        Y = rng.randint(0, 4, (16,)).astype(np.float32)
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

        tr_a = gluon.Trainer(net_a.collect_params(), "sgd", {"learning_rate": 0.1})
        with mx.autograd.record():
            la = loss_fn(net_a(mx.nd.array(X)), mx.nd.array(Y))
        la.backward()
        tr_a.step(16)

        tr_b = gluon.Trainer(net_b.collect_params(), "sgd", {"learning_rate": 0.1})
        amp.init_trainer(tr_b)
        with mx.autograd.record():
            lb = loss_fn(net_b(mx.nd.array(X)), mx.nd.array(Y))
            with amp.scale_loss(lb, tr_b) as scaled:
                pass
        scaled.backward()
        amp.unscale(tr_b)  # clipping-style flow
        tr_b.step(16)

        pa = net_a._collect_params_with_prefix()
        pb = net_b._collect_params_with_prefix()
        for k in pa:
            np.testing.assert_allclose(pa[k].data().asnumpy(),
                                       pb[k].data().asnumpy(),
                                       rtol=1e-4, atol=1e-5, err_msg=k)

    def test_amp_init_invalidates_spmd_step_cache(self):
        from incubator_mxnet_tpu.parallel import SPMDTrainer, make_mesh
        net = _net(seed=6)
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        tr = SPMDTrainer(net, loss_fn, "sgd", {"learning_rate": 0.1},
                         mesh=make_mesh())
        X = mx.nd.array(np.random.RandomState(0).randn(8, 8).astype(np.float32))
        Y = mx.nd.array((np.arange(8) % 4).astype(np.float32))
        tr.step(X, Y)
        assert tr._step_cache
        amp.init("bfloat16")
        assert not tr._step_cache  # must retrace under the AMP hook

    def test_contrib_amp_path(self):
        assert mx.contrib.amp is mx.amp

    def test_convert_rebuilds_grad_buffer(self):
        net = _net()
        amp.convert_hybrid_block(net, "bfloat16")
        p = list(net.collect_params().values())[0]
        assert p.data().dtype == np.dtype("bfloat16")
        assert p.grad().dtype == np.dtype("bfloat16")

    def test_init_trainer_idempotent(self):
        net = _net(seed=8)
        tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
        amp.init_trainer(tr)
        step1 = tr.step
        amp.init_trainer(tr)  # must not stack a second wrapper
        assert tr.step is step1


def test_scoped_disable():
    amp.init("bfloat16")
    try:
        x = mx.nd.ones((2, 4))
        w = mx.nd.ones((3, 4))
        assert mx.nd.FullyConnected(x, w, None, num_hidden=3, no_bias=True).dtype == np.dtype("bfloat16")
        with amp.disabled():
            out = mx.nd.FullyConnected(x, w, None, num_hidden=3, no_bias=True)
            assert out.dtype == np.float32
        assert mx.nd.FullyConnected(x, w, None, num_hidden=3, no_bias=True).dtype == np.dtype("bfloat16")
    finally:
        amp.disable()
